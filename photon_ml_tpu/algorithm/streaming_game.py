"""Out-of-core GAME training: streamed coordinate-descent sweeps with an
optional DuHL importance-ordered chunk schedule.

Reference parity: photon-lib algorithm/CoordinateDescent.scala:198-255 (the
GAME training loop this module re-runs chunk-wise) + data/avro/
AvroDataReader.scala (the reference never co-resides the full input on one
machine; Spark streams HDFS splits through executor tasks). The DuHL
working-set schedule has no reference analogue — it is the
duality-gap-ordered out-of-core strategy of Duenner et al.
(arXiv:1702.07005), applied at chunk granularity with the per-lane
convergence scalars the lane scheduler already reads (optim/common
.LaneTrace) as the importance signal.

Design (ISSUE 11):

- **Per-sample scalars stay host-resident; features stream.** The program
  owns [n] host score vectors (one per coordinate), labels/weights/base
  offsets, and per-RE-type entity indices — O(n) floats, the working set
  Snap ML's hierarchy keeps resident (arXiv:1803.06333). The O(n·d)
  feature blocks only ever exist one fixed-shape chunk at a time.
- **Entity-clustered chunks make RE solves exact.** The chunk plan
  (io/stream_reader.plan_entity_chunks) packs WHOLE entities per chunk,
  so each chunk's per-entity bucket solves see the identical padded
  blocks the in-core path builds (zero-weight cap padding is an exact
  no-op) — streamed GAME matches in-core ``train_distributed`` to float
  round-off (tests/test_streaming_game.py pins it). Every RE type is
  VERIFIED clustered before training; an entity spanning chunks fails
  fast (entity-cluster the input, or train that coordinate in-core).
- **The FE coordinate streams through the PR 7 contract.** Residual
  offsets overlay the chunk offsets host-side and the solve runs
  ``StreamingGLMObjective`` in host-loop mode — exact chunked epochs,
  decode double-buffered behind accumulation.
- **The 413 rule, mechanized.** Every chunk-consuming jit lives at module
  scope with the chunk pytree in its ARGUMENT list (``batch``); dev/
  lint_parity.py check 9 covers this module so the landmine stays
  structural on the GAME path too.
- **DuHL schedule (opt-in).** ``DuHLChunkSchedule`` keeps a fixed budget
  of gap-hottest chunks pinned (their decoded batches cached — FE epochs
  and RE solves hit the cache instead of the decoder), streams the cold
  tail round-robin, and re-ranks each sweep from the per-chunk aggregated
  gradient-norm scalars the bucket solves already return. Skipping a
  cold chunk's RE solve leaves its table rows — and therefore its scores
  — EXACT, just un-refreshed; on gap-skewed data the run reaches
  tolerance in far fewer chunk loads than uniform sweeps. ``schedule=None``
  (uniform order, every chunk every sweep, no cache) is the default and
  is pinned bitwise-identical to ``UniformChunkSchedule``.
- **Crash-safe resume.** Sweep-granular checkpoints ride
  ``io.checkpoint.commit_checkpoint`` (rank-0-gated, exchange-barriered
  when one is attached — lint check 10); the fingerprint pins the chunk
  plan AND the schedule mode/budget, so restoring under a different
  working-set budget fails fast naming the field. Scores are recomputed
  from the restored tables through the same jitted steps that produced
  them, so a resumed run continues bitwise.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from functools import partial
from typing import Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.algorithm.coordinates import (
    _mask_padding_lanes,
    _solve_bucket_entities,
)
from photon_ml_tpu.algorithm.streaming import (
    StreamingGLMObjective,
    _pack_f64,
    _pack_i64,
    _unpack_f64,
    _unpack_i64,
)
from photon_ml_tpu.data.batch import LabeledPointBatch, solve_dtype_of
from photon_ml_tpu.data.game_data import (
    group_entities_into_buckets,
    pack_bucket_lanes,
)
from photon_ml_tpu.io.checkpoint import commit_checkpoint, fingerprint_mismatch
from photon_ml_tpu.io.stream_reader import (
    DEFAULT_CHUNK_TIMEOUT,
    ChunkPrefetcher,
    ChunkSpec,
    GameChunk,
    entities_spanning_chunks,
)
from photon_ml_tpu.models.game import score_random_effect
from photon_ml_tpu.ops.losses import loss_for_task
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optim.optimizer import (
    OptimizerConfig,
    resolve_auto_optimizer,
)
from photon_ml_tpu.parallel.distributed import (
    FixedEffectStepSpec,
    GameTrainState,
    RandomEffectStepSpec,
)
from photon_ml_tpu.projector.projectors import ProjectorType
from photon_ml_tpu.telemetry import stream_counters, tracing
from photon_ml_tpu.telemetry.program_ledger import ledger_jit
from photon_ml_tpu.types import TaskType

Array = jax.Array

logger = logging.getLogger(__name__)

#: default entity size buckets — identical to
#: data.game_data.build_random_effect_dataset's default, so a streamed
#: chunk's per-entity blocks land in the same capacity classes the
#: in-core path pads to
DEFAULT_BUCKET_SIZES = (8, 32, 128, 512, 2048)


# ---------------------------------------------------------------------------
# The jit signatures chunks ride (module scope; chunk pytrees are the
# `batch` ARGUMENT — lint check 9)
# ---------------------------------------------------------------------------


@partial(ledger_jit, label="streaming_game/solve_re_chunk_bucket",
         static_argnames=("objective", "opt"))
def _solve_re_chunk_bucket(table, batch, *, objective, opt):
    """Solve one chunk-local entity bucket and scatter into the [E, d]
    table. ``batch``: features [e, cap, d], labels/weights/offsets
    [e, cap], entity_rows [e] (GLOBAL vocab rows; padding lanes carry the
    OOB sentinel E — gathers clamp, scatters drop). Returns
    (table, per-lane trace, per-lane coefficient movement ‖Δw‖) — the
    movement plus the trace's final gradient norm is the DuHL importance
    signal: a chunk whose entities stopped moving AND sit at small
    gradients has nothing left to contribute (near-zero extra cost — one
    [e] norm on arrays XLA already holds)."""
    w0 = table[batch["entity_rows"]]
    solved, trace = _solve_bucket_entities(
        objective, opt,
        batch["features"], batch["labels"], batch["weights"],
        batch["offsets"], w0,
    )
    trace = _mask_padding_lanes(trace, batch["entity_rows"], table.shape[0])
    movement = jnp.sqrt(jnp.sum((solved - w0) ** 2, axis=-1))
    return table.at[batch["entity_rows"]].set(solved), trace, movement


@partial(ledger_jit, label="streaming_game/fe_margin_chunk",
         static_argnames=("objective",))
def _fe_margin_chunk(w, batch, *, objective):
    """Pure FE margin of one chunk (no offsets) from normalized-space
    coefficients — the chunk-wise twin of GameTrainProgram's
    ``_fe_margin_score``."""
    norm = objective.normalization
    eff = norm.effective_coefficients(w)
    return batch["features"] @ eff - norm.margin_shift(eff)


@partial(ledger_jit, label="streaming_game/re_score_chunk")
def _re_score_chunk(table, batch):
    """One chunk's RE coordinate scores: x_i . table[entity_idx_i]
    (0 for absent entities / padding rows)."""
    return score_random_effect(table, batch["features"], batch["entity_idx"])


# ---------------------------------------------------------------------------
# Chunk schedules
# ---------------------------------------------------------------------------


class UniformChunkSchedule:
    """Every chunk, every sweep, in plan order — the PR-7-style uniform
    epoch, as a schedule object. Pins nothing; pinned bitwise-identical to
    ``schedule=None`` (tests/test_streaming_game.py)."""

    mode = "uniform"

    def __init__(self, num_chunks: int):
        self.num_chunks = int(num_chunks)

    def plan_sweep(self) -> list[int]:
        return list(range(self.num_chunks))

    def pinned(self) -> "set[int]":
        return set()

    def record(self, chunk: int, importance: float) -> None:
        pass

    def sweep_done(self) -> None:
        pass

    def state_dict(self) -> dict:
        return {"mode": self.mode}

    def load_state(self, state: dict) -> None:
        pass

    def fingerprint(self) -> dict:
        return {"schedule": self.mode}


@dataclasses.dataclass(frozen=True)
class DuHLScheduleConfig:
    """DuHL working-set budget: ``working_set_chunks`` gap-hottest chunks
    stay pinned (decoded batches cached) and re-solve every sweep;
    ``tail_chunks_per_sweep`` cold chunks rotate in round-robin so stale
    importances refresh and every chunk is revisited eventually.
    ``warmup_sweeps`` full sweeps run first: the importance signal is
    coefficient MOVEMENT, which is large everywhere on the very first
    solve (everything moves off the zero init) — only after a second
    visit does "still moving" separate gap-hot chunks from converged
    ones."""

    working_set_chunks: int
    tail_chunks_per_sweep: int = 1
    warmup_sweeps: int = 2

    def __post_init__(self):
        if self.working_set_chunks < 1:
            raise ValueError("working_set_chunks must be >= 1")
        if self.tail_chunks_per_sweep < 1:
            raise ValueError("tail_chunks_per_sweep must be >= 1")
        if self.warmup_sweeps < 1:
            raise ValueError("warmup_sweeps must be >= 1")


class DuHLChunkSchedule:
    """Importance-ordered chunk schedule (arXiv:1702.07005 at chunk
    granularity). The first ``warmup_sweeps`` sweeps visit everything
    (building a differential importance signal); later sweeps visit the
    top-``B`` chunks by importance plus the next ``t`` cold chunks
    round-robin. Importance = the per-chunk sum over valid lanes of
    coefficient movement + final gradient norm from the RE bucket solves
    — scalars the solve returns anyway (near-zero extra cost)."""

    mode = "duhl"

    def __init__(self, config: DuHLScheduleConfig, num_chunks: int):
        self.config = config
        self.num_chunks = int(num_chunks)
        self.importance = np.zeros(self.num_chunks, dtype=np.float64)
        self.cursor = 0
        self.sweeps_done = 0

    def _working_set(self) -> "list[int]":
        b = min(self.config.working_set_chunks, self.num_chunks)
        # stable argsort on negated importance: ties break on chunk index,
        # so the plan is deterministic (checkpoint resume replays it)
        return list(np.argsort(-self.importance, kind="stable")[:b])

    def plan_sweep(self) -> list[int]:
        if self.sweeps_done < self.config.warmup_sweeps:
            return list(range(self.num_chunks))
        visit = set(self._working_set())
        tail = [c for c in range(self.num_chunks) if c not in visit]
        for _ in range(min(self.config.tail_chunks_per_sweep, len(tail))):
            visit.add(tail[self.cursor % len(tail)])
            self.cursor += 1
        return sorted(visit)

    def pinned(self) -> "set[int]":
        if self.sweeps_done < self.config.warmup_sweeps:
            return set()
        return set(self._working_set())

    def record(self, chunk: int, importance: float) -> None:
        self.importance[chunk] = float(importance)

    def sweep_done(self) -> None:
        self.sweeps_done += 1

    def state_dict(self) -> dict:
        return {
            "mode": self.mode,
            "importance": [float(x) for x in self.importance],
            "cursor": int(self.cursor),
            "sweeps_done": int(self.sweeps_done),
        }

    def load_state(self, state: dict) -> None:
        if state.get("mode") != self.mode:
            raise ValueError(
                f"schedule state holds mode {state.get('mode')!r}, this run "
                f"is {self.mode!r}"
            )
        self.importance = np.asarray(state["importance"], dtype=np.float64)
        self.cursor = int(state["cursor"])
        self.sweeps_done = int(state["sweeps_done"])

    def fingerprint(self) -> dict:
        return {
            "schedule": self.mode,
            "working_set_chunks": int(self.config.working_set_chunks),
            "tail_chunks_per_sweep": int(self.config.tail_chunks_per_sweep),
            "warmup_sweeps": int(self.config.warmup_sweeps),
        }


# ---------------------------------------------------------------------------
# Working-set chunk cache
# ---------------------------------------------------------------------------


class _ChunkCache:
    """Load-through cache over a GAME chunk source. Only PINNED chunks
    (the DuHL working set) are retained — host batch plus the
    device-placed FE feature block, so a pinned chunk's FE epochs re-read
    HBM-resident features instead of re-decoding and re-transferring.
    ``loads`` counts source decodes (the DuHL evidence metric); cache hits
    are free. Thread-safe: the FE prefetcher's producer thread loads
    through here."""

    def __init__(self, source):
        self.source = source
        self.loads = 0
        #: rows of zero-padding applied to every FE feature block (mesh
        #: divisibility) — a PROGRAM constant set once at build, so the
        #: cached placed blocks always carry the one shape every consumer
        #: expects (margins slice [:num_records] either way)
        self.fe_pad = 0
        self._store: dict[int, GameChunk] = {}
        self._fe_device: dict[int, Array] = {}
        self._pinned: "set[int]" = set()
        self._lock = threading.Lock()

    def get(self, index: int) -> GameChunk:
        with self._lock:
            cached = self._store.get(index)
        if cached is not None:
            return cached
        chunk = self.source.load(self.source.specs[index])
        with self._lock:
            self.loads += 1
            if index in self._pinned:
                self._store[index] = chunk
        return chunk

    def fe_features(self, index: int, shard: str):
        """FE feature block of one chunk, zero-padded by the program's
        ``fe_pad`` rows (mesh divisibility); device-resident for pinned
        chunks ("pinned in HBM": padding happens BEFORE placement, so
        mesh runs never round-trip the pinned block back to host), a
        plain host array otherwise."""
        with self._lock:
            placed = self._fe_device.get(index)
            pinned = index in self._pinned
        if placed is not None:
            return placed
        chunk = self.get(index)
        feats = chunk.features[shard]
        if self.fe_pad:
            feats = np.pad(feats, ((0, self.fe_pad), (0, 0)))
        if pinned:
            placed = jnp.asarray(feats)
            with self._lock:
                self._fe_device[index] = placed
            return placed
        return feats

    def set_pinned(self, pinned: "set[int]") -> None:
        with self._lock:
            self._pinned = set(pinned)
            for idx in list(self._store):
                if idx not in self._pinned:
                    del self._store[idx]
            for idx in list(self._fe_device):
                if idx not in self._pinned:
                    del self._fe_device[idx]


class _FixedEffectChunkView:
    """The FE coordinate's view of the GAME chunk stream: a dense
    ``ChunkSource`` whose every load overlays the CURRENT residual offsets
    (other coordinates' scores) onto the chunk's base offsets host-side —
    so the existing ``StreamingGLMObjective`` runs the FE solve unchanged
    (PR 7 contract: exact chunked epochs, one module-level jitted
    accumulator, chunks as jit ARGUMENTS)."""

    sparse = False

    def __init__(self, cache: _ChunkCache, shard: str,
                 residual: np.ndarray, *, pad_multiple: int = 1):
        self._cache = cache
        self._shard = shard
        self._residual = residual
        src = cache.source
        self.specs: "list[ChunkSpec]" = src.specs
        # mesh runs shard the chunk's sample axis; pad to the data-axis
        # multiple with zero-weight rows (an exact no-op, and constant per
        # source so every chunk keeps the one jit signature)
        self._pad = (-src.chunk_rows) % max(1, int(pad_multiple))
        self.chunk_rows = src.chunk_rows + self._pad
        self.dim = src.dims[shard]

    @property
    def num_chunks(self) -> int:
        return len(self.specs)

    @property
    def total_records(self) -> int:
        return int(sum(s.num_records for s in self.specs))

    def load(self, spec: ChunkSpec) -> LabeledPointBatch:
        chunk = self._cache.get(spec.index)
        rows = chunk.rows
        safe = np.maximum(rows, 0)
        # the residual ALREADY includes the base offsets (it is
        # base + Σ other coordinates' scores, the CD recursion's
        # offsets_excluding) — it REPLACES the chunk's base offsets here;
        # padding rows (-1) carry 0 like every padded field
        offsets = np.where(
            rows >= 0, self._residual[safe], 0.0
        ).astype(chunk.offsets.dtype)
        # the cache pads the feature block before device placement; the
        # per-sample vectors pad here (host, cheap, fresh per epoch)
        features = self._cache.fe_features(spec.index, self._shard)
        labels, weights = chunk.labels, chunk.weights
        if self._pad:
            pad = self._pad
            labels = np.pad(labels, (0, pad))
            offsets = np.pad(offsets, (0, pad))
            weights = np.pad(weights, (0, pad))
        return LabeledPointBatch(
            features=features,
            labels=labels,
            offsets=offsets,
            weights=weights,
        )


# ---------------------------------------------------------------------------
# The program
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class StreamingGameResult:
    state: GameTrainState
    losses: "list[float]"
    sweeps: int
    chunk_loads: int
    chunk_visits: int

    def __iter__(self):
        return iter((self.state, self.losses))


class StreamingGameProgram:
    """Out-of-core GAME coordinate descent over an entity-clustered chunk
    source (io/stream_reader.GameArrayChunkSource / GameAvroChunkSource).

    Covers the production streamed surface: one dense primary FE
    coordinate plus IDENTITY random-effect coordinates, no normalization
    riders (projected/compact/MF coordinates keep the in-core paths —
    their build steps materialize O(n·d) state this module exists to
    avoid). The sweep replays GameTrainProgram's Gauss-Seidel recursion in
    the same update order with the same residual algebra, so the streamed
    fit matches in-core ``train_distributed`` to float round-off.
    """

    def __init__(
        self,
        task: TaskType,
        source,
        fe: FixedEffectStepSpec,
        re_specs: Sequence[RandomEffectStepSpec] = (),
        *,
        num_entities: Mapping[str, int] | None = None,
        bucket_sizes: Sequence[int] = DEFAULT_BUCKET_SIZES,
        schedule=None,
        prefetch: bool = True,
        mesh=None,
        exchange=None,
        partition=None,
        retry_policy=None,
        scalars: Mapping[str, object] | None = None,
    ):
        self.task = task
        self.source = source
        loss = loss_for_task(task)
        self._loss = loss
        # AUTO resolution mirrors GameTrainProgram: LBFGS on the (big-d,
        # host-loop streamed) FE; NEWTON on eligible RE bucket solves
        self.fe = dataclasses.replace(
            fe, optimizer=resolve_auto_optimizer(
                fe.optimizer, loss=loss, small_dense=False
            ),
        )
        self.re_specs = tuple(
            dataclasses.replace(
                s, optimizer=resolve_auto_optimizer(
                    s.optimizer, loss=loss, small_dense=True
                ),
            )
            for s in re_specs
        )
        for s in self.re_specs:
            if s.projector != ProjectorType.IDENTITY:
                raise ValueError(
                    f"streamed random-effect coordinate '{s.re_type}' uses "
                    f"projector {s.projector.name}; the streamed surface "
                    "covers IDENTITY — train projected coordinates in-core "
                    "(train_distributed)"
                )
        self.bucket_sizes = tuple(int(b) for b in sorted(bucket_sizes))
        self.num_entities = dict(num_entities or {})
        self.schedule = schedule
        self.prefetch = bool(prefetch)
        self.mesh = mesh
        self.exchange = exchange
        # ISSUE 17: the exchange-agreed multi-rank plan. None (or a
        # 1-rank partition) keeps every single-rank path bitwise — the
        # chunk-id mapping below degenerates to the identity and no
        # cross-rank exchange op runs. An exchange WITHOUT a partition is
        # the ISSUE 15 wiring (checkpoint barriers only) and must stay
        # exactly that: cross-rank sums are keyed off the partition, never
        # off exchange presence.
        self.partition = partition
        self._multi_rank = partition is not None and partition.num_ranks > 1
        if self._multi_rank and exchange is None:
            raise ValueError(
                "a multi-rank GameStreamPartition needs the exchange it "
                "was agreed over (pass exchange=)"
            )
        if partition is not None:
            self._chunk_lo, self._chunk_hi = partition.chunk_range()
            self._num_chunks_global = int(partition.num_chunks)
        else:
            self._chunk_lo, self._chunk_hi = 0, source.num_chunks
            self._num_chunks_global = int(source.num_chunks)
        if self._multi_rank:
            missing = [
                s.re_type for s in self.re_specs
                if s.re_type not in self.num_entities
            ]
            if missing:
                raise ValueError(
                    f"partitioned streamed GAME needs explicit num_entities "
                    f"for {missing} — each rank sees only its local "
                    "entities, so table sizes must come from the agreed "
                    "global vocabs (num_entities={t: len(vocabs[t])})"
                )
        self.retry_policy = retry_policy
        self._cache = _ChunkCache(source)
        if mesh is not None:
            data_axis = int(mesh.shape[mesh.axis_names[0]])
            self._cache.fe_pad = (-source.chunk_rows) % data_axis
        self._fe_objective = GLMObjective(
            loss, l2_weight=fe.l2_weight, use_pallas=False
        )
        self._re_objectives = {
            s.re_type: GLMObjective(
                loss, l2_weight=s.l2_weight, use_pallas=False
            )
            for s in self.re_specs
        }
        #: lane schedulers per (re_type, chunk): per-chunk bucket blocks
        #: are distinct jit/compaction universes, so each chunk keeps its
        #: own probe/rescue state (strictly opt-in via
        #: OptimizerConfig.scheduler, like the in-core paths)
        self._lane_schedulers: dict = {}
        # sweep order: primary FE then REs in spec order — the
        # GameTrainProgram default (FE, extras, REs, MFs) restricted to
        # the streamed surface
        self.update_order = (
            (self.fe.feature_shard_id,)
            + tuple(s.re_type for s in self.re_specs)
        )
        # coordinate names share one namespace (score slots, residual
        # skips) — a collision would silently corrupt the residual
        # algebra; same guard as GameTrainProgram.__init__
        dupes = {
            n for n in self.update_order if self.update_order.count(n) > 1
        }
        if dupes:
            raise ValueError(
                f"coordinate names must be unique across the FE feature "
                f"shard and RE types (duplicates: {sorted(dupes)})"
            )
        self._re_by_name = {s.re_type: s for s in self.re_specs}
        self._scalars_arg = scalars
        self._scan_scalars()
        self._verify_clustering()
        if self._multi_rank:
            self._verify_rank_entity_partition()

    # -- one-time host scans --------------------------------------------------

    def _row_plan_from_metadata(self):
        """The per-chunk global row arrays straight from source metadata
        (no decode): in-memory sources carry an explicit ``row_plan``;
        record-ordered file sources carry per-chunk ``record_starts``."""
        src = self.source
        if getattr(src, "row_plan", None) is not None:
            return [np.asarray(r) for r in src.row_plan]
        if getattr(src, "record_starts", None) is not None:
            return [
                np.arange(start, start + spec.num_records, dtype=np.int64)
                for start, spec in zip(src.record_starts, src.specs)
            ]
        return None

    def _scan_scalars(self) -> None:
        """Make the [n] per-sample scalars the sweeps need host-resident
        (labels/weights/base offsets, entity indices) — O(n) floats,
        never O(n·d). Fast paths avoid decoding any feature block: the
        caller may pass ``scalars`` (io/stream_reader.scan_game_stream
        collects them during its vocab pass — the driver route), and
        in-memory sources expose the arrays directly; only a source with
        neither falls back to one decode pass over the chunk plan."""
        n = self.source.total_records
        self.n = n
        src = self.source
        scalars = self._scalars_arg
        if scalars is None and (
            getattr(src, "labels", None) is not None
            and getattr(src, "entity_idx", None) is not None
        ):
            scalars = {
                "labels": src.labels,
                "offsets": src.offsets,
                "weights": src.weights,
                "entity_idx": src.entity_idx,
            }
        row_plan = self._row_plan_from_metadata()
        if scalars is not None and row_plan is not None:
            self.labels = np.asarray(scalars["labels"])
            self.base_offsets = np.asarray(
                scalars["offsets"], dtype=self.labels.dtype
            )
            self.weights = np.asarray(
                scalars["weights"], dtype=self.labels.dtype
            )
            self.entity_idx = {
                t: np.asarray(v, dtype=np.int32)
                for t, v in scalars["entity_idx"].items()
            }
            if len(self.labels) != n:
                raise ValueError(
                    f"scalars cover {len(self.labels)} records but the "
                    f"chunk plan holds {n}"
                )
            self.dtype = self.labels.dtype
            self.solve_dtype = solve_dtype_of(self.dtype)
            self._row_plan = row_plan
            seen = np.zeros(n, dtype=bool)
            for i, rows in enumerate(row_plan):
                if seen[rows].any():
                    raise ValueError(
                        f"chunk {i} re-covers sample rows already assigned "
                        "to another chunk — the plan must partition the "
                        "sample axis"
                    )
                seen[rows] = True
            if not seen.all():
                raise ValueError(
                    f"chunk plan covers {int(seen.sum())}/{n} sample rows"
                )
            for s in self.re_specs:
                if s.re_type not in self.entity_idx:
                    raise ValueError(
                        f"random-effect coordinate '{s.re_type}' has no "
                        "entity index column in the chunk stream"
                    )
                if s.re_type not in self.num_entities:
                    self.num_entities[s.re_type] = int(
                        self.entity_idx[s.re_type].max() + 1
                    )
            return
        dtype = None
        self.labels = None
        # the scan also pins the plan's row universe: a plan with
        # overlapping or missing rows would corrupt the score algebra
        # silently
        self._row_plan = [None] * self.source.num_chunks
        seen = np.zeros(n, dtype=bool)
        with tracing.span("stream_game/scan", cat="stream",
                          chunks=self.source.num_chunks):
            for spec in self.source.specs:
                chunk = self._cache.get(spec.index)
                if self.labels is None:
                    dtype = chunk.labels.dtype
                    self.labels = np.zeros(n, dtype)
                    self.base_offsets = np.zeros(n, dtype)
                    self.weights = np.zeros(n, dtype)
                    self.entity_idx = {
                        t: np.full(n, -1, np.int32) for t in chunk.entity_idx
                    }
                m = chunk.num_records
                rows = chunk.rows[:m]
                if seen[rows].any():
                    raise ValueError(
                        f"chunk {spec.index} re-covers sample rows already "
                        "assigned to another chunk — the plan must "
                        "partition the sample axis"
                    )
                seen[rows] = True
                self._row_plan[spec.index] = np.asarray(rows)
                self.labels[rows] = chunk.labels[:m]
                self.base_offsets[rows] = chunk.offsets[:m]
                self.weights[rows] = chunk.weights[:m]
                for t, idx in chunk.entity_idx.items():
                    self.entity_idx[t][rows] = idx[:m]
        if self.labels is None:
            raise ValueError("streamed GAME needs a non-empty chunk plan")
        self.dtype = dtype
        self.solve_dtype = solve_dtype_of(dtype)
        if not seen.all():
            raise ValueError(
                f"chunk plan covers {int(seen.sum())}/{n} sample rows"
            )
        for s in self.re_specs:
            if s.re_type not in self.entity_idx:
                raise ValueError(
                    f"random-effect coordinate '{s.re_type}' has no entity "
                    "index column in the chunk stream"
                )
            if s.re_type not in self.num_entities:
                self.num_entities[s.re_type] = int(
                    self.entity_idx[s.re_type].max() + 1
                )

    def _verify_clustering(self) -> None:
        for s in self.re_specs:
            spanning = entities_spanning_chunks(
                self._row_plan, self.entity_idx[s.re_type]
            )
            if len(spanning):
                raise ValueError(
                    f"random-effect coordinate '{s.re_type}': "
                    f"{len(spanning)} entities span chunk boundaries (e.g. "
                    f"vocab rows {spanning[:5].tolist()}) — a per-chunk "
                    "solve would train them on partial data. Entity-cluster "
                    "the chunk plan by this type (cluster_by), sort the "
                    "input by it, or train this coordinate in-core."
                )

    def _verify_rank_entity_partition(self) -> None:
        """The multi-rank twin of :meth:`_verify_clustering`: every RE
        entity's rows must co-reside on ONE rank (whole-chunk assignment
        guarantees it for the cluster column; other RE types could still
        straddle the rank boundary). An overlap would let two ranks solve
        the same entity on partial data and the rank-order table sync
        silently keep the last writer — fail fast instead. One allgather
        of each rank's present entity rows (model-sized, like the vocab
        agreement)."""
        if not self.re_specs:
            return
        payload = {}
        for s in self.re_specs:
            idx = self.entity_idx[s.re_type]
            payload[s.re_type] = _pack_i64(
                np.unique(idx[idx >= 0]).astype(np.int64)
            )
        gathered = self.exchange.allgather(
            "stream_game/entity_partition", payload
        )
        for s in self.re_specs:
            per_rank = [_unpack_i64(g[s.re_type]) for g in gathered]
            ids, counts = np.unique(
                np.concatenate(per_rank), return_counts=True
            )
            overlap = ids[counts > 1]
            if len(overlap):
                owners = [
                    r for r, present in enumerate(per_rank)
                    if np.isin(overlap[:5], present).any()
                ]
                raise ValueError(
                    f"random-effect coordinate '{s.re_type}': "
                    f"{len(overlap)} entities have rows on more than one "
                    f"rank (e.g. vocab rows {overlap[:5].tolist()} on ranks "
                    f"{owners}) — a per-rank solve would train them on "
                    "partial data. Sort the input by the cluster column, "
                    "nest this type inside it, or train this coordinate "
                    "in-core."
                )

    # -- state / scores -------------------------------------------------------

    def init_state(self) -> GameTrainState:
        fe_dim = self.source.dims[self.fe.feature_shard_id]
        return GameTrainState(
            fe_coefficients=jnp.zeros((fe_dim,), dtype=self.solve_dtype),
            re_tables={
                s.re_type: jnp.zeros(
                    (self.num_entities[s.re_type],
                     self.source.dims[s.feature_shard_id]),
                    dtype=self.solve_dtype,
                )
                for s in self.re_specs
            },
        )

    def _zero_scores(self) -> "dict[str, np.ndarray]":
        return {
            name: np.zeros(self.n, self.solve_dtype)
            for name in self.update_order
        }

    def _residual(self, scores, skip=None) -> np.ndarray:
        """base offsets + every coordinate score except ``skip``, summed in
        canonical update order — the identical accumulation order
        GameTrainProgram._sum_scores uses, element-wise on host."""
        total = self.base_offsets.astype(self.solve_dtype)
        for name in self.update_order:
            if name != skip:
                total = total + scores[name]
        return total

    def _refresh_fe_scores(self, scores, fe_w) -> None:
        """Recompute the FE margin for every sample, chunk-wise, through
        the module-level jitted step."""
        shard = self.fe.feature_shard_id
        for spec in self.source.specs:
            batch = {
                "features": self._cache.fe_features(spec.index, shard),
            }
            margins = np.asarray(
                _fe_margin_chunk(fe_w, batch, objective=self._fe_objective)
            )
            m = spec.num_records
            scores[shard][self._row_plan[spec.index]] = margins[:m].astype(
                self.solve_dtype
            )

    def _refresh_re_scores_chunk(self, scores, re_type, table, chunk,
                                 spec) -> None:
        s = self._re_by_name[re_type]
        batch = {
            "features": chunk.features[s.feature_shard_id],
            "entity_idx": chunk.entity_idx[re_type],
        }
        margins = np.asarray(_re_score_chunk(table, batch))
        m = spec.num_records
        scores[re_type][self._row_plan[spec.index]] = margins[:m].astype(
            self.solve_dtype
        )

    def refresh_all_scores(self, state: GameTrainState) -> "dict[str, np.ndarray]":
        """Scores of every coordinate at ``state`` — used on resume/warm
        start (a zero state's scores are exactly zero, no pass needed).
        Chunk-outer so each chunk decodes ONCE for the FE margin and
        every RE coordinate (the cache retains only pinned chunks)."""
        scores = self._zero_scores()
        shard = self.fe.feature_shard_id
        for spec in self.source.specs:
            chunk = self._cache.get(spec.index)
            m = spec.num_records
            rows = self._row_plan[spec.index]
            margins = np.asarray(_fe_margin_chunk(
                state.fe_coefficients, {"features": chunk.features[shard]},
                objective=self._fe_objective,
            ))
            scores[shard][rows] = margins[:m].astype(self.solve_dtype)
            for s in self.re_specs:
                self._refresh_re_scores_chunk(
                    scores, s.re_type, state.re_tables[s.re_type], chunk,
                    spec,
                )
        return scores

    # -- coordinate solves ----------------------------------------------------

    def _solve_fe(self, scores, fe_w) -> Array:
        residual = self._residual(scores, skip=self.fe.feature_shard_id)
        pad_multiple = 1
        if self.mesh is not None:
            pad_multiple = int(self.mesh.shape[self.mesh.axis_names[0]])
        view = _FixedEffectChunkView(
            self._cache, self.fe.feature_shard_id, residual,
            pad_multiple=pad_multiple,
        )
        objective = StreamingGLMObjective(
            view, self._loss,
            l2_weight=self.fe.l2_weight,
            mesh=self.mesh,
            # multi-rank: per-rank partial value/grad/Hv summed IN RANK
            # ORDER through the exchange every epoch (the PR 7 accumulator
            # rule) — every rank evaluates the identical global objective,
            # so the host-loop solver takes identical steps on every rank.
            # Keyed off the PARTITION, never off exchange presence: a
            # coordinated-recovery exchange on a full program must not
            # double-count (each such rank already streams ALL chunks).
            exchange=self.exchange if self._multi_rank else None,
            prefetch=self.prefetch,
            retry_policy=self.retry_policy,
        )
        from photon_ml_tpu.optim.optimizer import solve

        result = solve(self.fe.optimizer, objective, fe_w, host_loop=True)
        return result.coefficients

    def _chunk_blocks(self, chunk: GameChunk, re_type: str,
                      residual_local: np.ndarray):
        """Chunk-local entity buckets, packed exactly like
        build_random_effect_dataset's IDENTITY path (same bucket sizes,
        same lane layout, ascending row order per entity), with lanes
        padded to the next power of two so the per-chunk jit signatures
        stay bounded across chunks and sweeps. ``residual_local`` is the
        CD residual in chunk-local row coordinates ([chunk_rows], padding
        rows 0)."""
        s = self._re_by_name[re_type]
        idx = chunk.entity_idx[re_type]
        m = chunk.num_records
        feats = chunk.features[s.feature_shard_id]
        labels, weights = chunk.labels, chunk.weights
        # chunk.rows double as stable sample ids: the streamed surface
        # keeps build_game_dataset's default unique_ids (= row index)
        per_bucket = group_entities_into_buckets(
            idx[:m], chunk.rows[:m], bucket_sizes=self.bucket_sizes
        )
        num_rows = self.num_entities[re_type]
        blocks = []
        for cap, members in per_bucket.items():
            if not members:
                continue
            e = len(members)
            e_pad = 1 << (e - 1).bit_length()
            be, rows_concat, lane, slot = pack_bucket_lanes(members)
            bf = np.zeros((e_pad, cap, feats.shape[1]), feats.dtype)
            bl = np.zeros((e_pad, cap), labels.dtype)
            bw = np.zeros((e_pad, cap), weights.dtype)
            bo = np.zeros((e_pad, cap), residual_local.dtype)
            bf[lane, slot] = feats[rows_concat]
            bl[lane, slot] = labels[rows_concat]
            bw[lane, slot] = weights[rows_concat]
            bo[lane, slot] = residual_local[rows_concat]
            ents = np.full((e_pad,), num_rows, np.int32)  # OOB sentinel
            ents[:e] = be
            blocks.append({
                "features": bf, "labels": bl, "weights": bw,
                "offsets": bo, "entity_rows": ents,
            })
        return blocks

    def _solve_re_chunk(self, re_type, table, chunk, spec, residual_local,
                        final_sweep: bool):
        """All of one chunk's entity buckets for one RE coordinate.
        Returns (table, importance): importance = Σ valid lanes'
        coefficient movement + final gradient norm — the DuHL gap signal,
        read from scalars the solve computes anyway."""
        s = self._re_by_name[re_type]
        opt = s.optimizer
        objective = self._re_objectives[re_type]
        if opt.scheduler is not None:
            return self._solve_re_chunk_scheduled(
                re_type, table, chunk, spec, residual_local, final_sweep
            )
        importance = 0.0
        for batch in self._chunk_blocks(chunk, re_type, residual_local):
            table, trace, movement = _solve_re_chunk_bucket(
                table, batch, objective=objective, opt=opt
            )
            valid = np.asarray(trace.valid)
            signal = np.asarray(movement) + np.asarray(trace.gradient_norm)
            importance += float(np.where(valid, signal, 0.0).sum())
        return table, importance

    def _solve_re_chunk_scheduled(self, re_type, table, chunk, spec,
                                  residual_local, final_sweep):
        """Probe/rescue lane scheduling per chunk
        (algorithm/lane_scheduler.py — opt-in via
        OptimizerConfig.scheduler, exactly like the in-core paths).
        ``sample_rows`` and the offsets vector both live in CHUNK-LOCAL
        row coordinates — the scheduler only ever gathers offsets through
        them, so the pairing is self-consistent."""
        from photon_ml_tpu.algorithm.lane_scheduler import LaneScheduler

        s = self._re_by_name[re_type]
        key = (re_type, spec.index)
        scheduler = self._lane_schedulers.get(key)
        if scheduler is None or scheduler.config != s.optimizer.scheduler:
            scheduler = LaneScheduler(s.optimizer.scheduler)
            self._lane_schedulers[key] = scheduler
        blocks = []
        m = chunk.num_records
        idx = chunk.entity_idx[re_type]
        feats = chunk.features[s.feature_shard_id]
        per_bucket = group_entities_into_buckets(
            idx[:m], chunk.rows[:m], bucket_sizes=self.bucket_sizes
        )
        for cap, members in per_bucket.items():
            if not members:
                continue
            e = len(members)
            be, rows_concat, lane, slot = pack_bucket_lanes(members)
            bf = np.zeros((e, cap, feats.shape[1]), feats.dtype)
            bl = np.zeros((e, cap), chunk.labels.dtype)
            bw = np.zeros((e, cap), chunk.weights.dtype)
            bs = np.full((e, cap), -1, np.int32)
            bf[lane, slot] = feats[rows_concat]
            bl[lane, slot] = chunk.labels[rows_concat]
            bw[lane, slot] = chunk.weights[rows_concat]
            bs[lane, slot] = rows_concat
            blocks.append({
                "features": bf, "labels": bl, "weights": bw,
                "sample_rows": bs, "entity_rows": be,
            })
        # movement term computed around the scheduler call (its traces
        # carry no Δw): same movement + gradient-norm signal as the
        # unscheduled path, so both composition modes rank identically
        moved_rows = np.concatenate(
            [np.asarray(b["entity_rows"]) for b in blocks]
        ) if blocks else np.zeros(0, np.int32)
        before = np.asarray(table)[moved_rows]
        table, traces, _stats = scheduler.solve(
            self._re_objectives[re_type], s.optimizer, blocks,
            jnp.asarray(residual_local), table,
            projector=ProjectorType.IDENTITY, final_sweep=final_sweep,
        )
        after = np.asarray(table)[moved_rows]
        importance = float(
            np.sqrt(((after - before) ** 2).sum(axis=-1)).sum()
        )
        for trace in traces:
            valid = np.asarray(trace.valid)
            gnorm = np.asarray(trace.gradient_norm)
            importance += float(np.where(valid, gnorm, 0.0).sum())
        return table, importance

    # -- the sweep ------------------------------------------------------------

    def _weighted_loss(self, scores) -> float:
        margins = self._residual(scores)
        losses = self._loss.loss(jnp.asarray(margins),
                                 jnp.asarray(self.labels))
        wloss = float(jnp.sum(jnp.asarray(self.weights) * losses))
        wsum = float(self.weights.sum())
        if self._multi_rank:
            # rank-order f64 sum of (Σw·loss, Σw) — the loss every rank
            # reports (and plateau-stops on) is the GLOBAL training loss,
            # identical on every rank
            gathered = self.exchange.allgather(
                "stream_game/loss", {"acc": _pack_f64(
                    np.array([wloss, wsum], np.float64)
                )}
            )
            wloss, wsum = 0.0, 0.0
            for g in gathered:  # rank order — the exchange contract
                part = _unpack_f64(g["acc"])
                wloss += float(part[0])
                wsum += float(part[1])
        return wloss / max(wsum, 1.0)

    def _chunk_residual_local(self, scores, rows, m, skip) -> np.ndarray:
        """The CD residual for ONE chunk's rows, in chunk-local
        coordinates ([chunk_rows], padding rows 0): base offsets + every
        coordinate score except ``skip``, summed in the same canonical
        update order as :meth:`_residual` — elementwise-identical values,
        sliced instead of full-length so the sweep stays O(n) per
        coordinate, not O(n · num_chunks)."""
        vals = self.base_offsets[rows].astype(self.solve_dtype)
        for name in self.update_order:
            if name != skip:
                vals = vals + scores[name][rows]
        out = np.zeros(self.source.chunk_rows, self.solve_dtype)
        out[:m] = vals
        return out

    def _sweep(self, state: GameTrainState, scores, visit, final_sweep):
        """One Gauss-Seidel CD sweep over the streamed coordinates —
        GameTrainProgram._step_impl's recursion, chunk-wise. The RE phase
        is CHUNK-outer (each visited chunk decodes once for every RE
        coordinate): chunks partition the sample axis and an entity's
        rows co-reside in its chunk, so interleaving coordinates within a
        chunk sees exactly the residual values the coordinate-outer order
        would — bit-identical updates, (num_coordinates)x less I/O."""
        fe_w = state.fe_coefficients
        tables = dict(state.re_tables)
        with tracing.span("stream_game/fe_solve", cat="stream"):
            fe_w = self._solve_fe(scores, fe_w)
            self._refresh_fe_scores(scores, fe_w)
        re_names = [
            name for name in self.update_order
            if name != self.fe.feature_shard_id
        ]
        # importance accumulates ACROSS RE coordinates before recording:
        # a chunk gap-hot for any coordinate must stay in the working set
        # (per-coordinate record() calls would let the last coordinate
        # overwrite the others' signal)
        chunk_importance: dict[int, float] = {}
        updated_rows: dict[str, set] = {name: set() for name in re_names}
        for chunk_index in visit:
            spec = self.source.specs[chunk_index]
            chunk = self._cache.get(chunk_index)
            rows = self._row_plan[chunk_index]
            for name in re_names:
                with tracing.span("stream_game/re_chunk", cat="stream",
                                  coordinate=name, chunk=chunk_index):
                    residual = self._chunk_residual_local(
                        scores, rows, spec.num_records, skip=name
                    )
                    tables[name], importance = self._solve_re_chunk(
                        name, tables[name], chunk, spec, residual,
                        final_sweep,
                    )
                    self._refresh_re_scores_chunk(
                        scores, name, tables[name], chunk, spec
                    )
                if self._multi_rank:
                    idx = chunk.entity_idx[name][:spec.num_records]
                    updated_rows[name].update(
                        np.unique(idx[idx >= 0]).tolist()
                    )
                chunk_importance[chunk_index] = (
                    chunk_importance.get(chunk_index, 0.0) + importance
                )
        # the schedule speaks GLOBAL chunk ids (identical state on every
        # rank); local chunk k is global k + chunk_lo (identity when
        # unpartitioned)
        importance_global = {
            ci + self._chunk_lo: imp for ci, imp in chunk_importance.items()
        }
        if self._multi_rank:
            tables = self._sync_re_tables(tables, updated_rows)
            importance_global = self._merge_importance(importance_global)
        for chunk_index in sorted(importance_global):
            self.schedule.record(chunk_index, importance_global[chunk_index])
        return GameTrainState(fe_coefficients=fe_w, re_tables=tables)

    def _sync_re_tables(self, tables, updated_rows):
        """Rank-order merge of this sweep's RE table updates: each rank
        ships only the (row, value) pairs its chunks touched; every rank
        applies every rank's rows in rank order. Rows partition across
        ranks (whole-entity chunk assignment, verified at build time), so
        the merge is EXACT — after it, every rank holds the identical
        global tables, which is what lets the rank-0-gated checkpoint
        commit and the final model stay complete on every rank. The
        f32→f64→f32 round trip through the exchange is value-exact."""
        payload = {}
        for name, rows in updated_rows.items():
            rows_arr = np.asarray(sorted(rows), np.int64)
            vals = np.asarray(tables[name])[rows_arr]
            payload[name] = {
                "rows": _pack_i64(rows_arr),
                "vals": _pack_f64(vals.ravel()),
            }
        with tracing.span("stream_game/re_sync", cat="stream"):
            gathered = self.exchange.allgather("stream_game/re_sync", payload)
        out = {}
        for name, table in tables.items():
            if name not in payload:
                out[name] = table
                continue
            merged = np.asarray(table).copy()
            width = merged.shape[1]
            for g in gathered:  # rank order — the exchange contract
                rows_arr = _unpack_i64(g[name]["rows"])
                if len(rows_arr) == 0:
                    continue
                vals = _unpack_f64(g[name]["vals"]).reshape(-1, width)
                merged[rows_arr] = vals.astype(merged.dtype)
            out[name] = jnp.asarray(merged)
        return out

    def _merge_importance(self, importance_global):
        """ONE allgathered DuHL importance signal (arXiv:2004.02414's
        nonrandom-partition fix): every rank sees every chunk's importance
        before any schedule records it, so pin/evict decisions are a pure
        function of the same global signal on every rank — rank-local
        ranking is the measured 12-vs-8-sweeps footgun. Chunk-id keys are
        disjoint across ranks (each rank visits only its own range)."""
        payload = {
            "imp": {str(ci): float(v) for ci, v in importance_global.items()}
        }
        with tracing.span("stream_game/duhl_importance", cat="stream"):
            gathered = self.exchange.allgather(
                "stream_game/duhl_importance", payload
            )
        merged: dict[int, float] = {}
        for g in gathered:  # rank order (keys disjoint; order is for form)
            for ci, v in g["imp"].items():
                merged[int(ci)] = float(v)
        return merged

    # -- checkpoint plumbing --------------------------------------------------

    def _fingerprint(self) -> dict:
        sched = (
            {"schedule": "uniform"} if self.schedule is None
            else self.schedule.fingerprint()
        )

        def opt_fields(opt: OptimizerConfig) -> list:
            # EVERYTHING a restored sweep is only valid under — a changed
            # tolerance/history would silently resume a different solve
            # (the PR 8 hardening rule, applied to every coordinate)
            return [
                opt.optimizer_type.name,
                int(opt.max_iterations),
                float(opt.tolerance),
                None if opt.rel_function_tolerance is None
                else float(opt.rel_function_tolerance),
                int(opt.history),
                int(opt.max_cg_iterations),
                float(opt.l1_weight),
                opt.scheduler is not None,
            ]

        return {
            "kind": "game_streaming",
            "task": self.task.name,
            "fe": [
                self.fe.feature_shard_id,
                float(self.fe.l2_weight),
                *opt_fields(self.fe.optimizer),
            ],
            "coordinates": [
                [s.re_type, s.feature_shard_id, float(s.l2_weight),
                 *opt_fields(s.optimizer)]
                for s in self.re_specs
            ],
            "bucket_sizes": list(self.bucket_sizes),
            # GLOBAL geometry when partitioned — every rank's fingerprint
            # must be identical (rank 0 saves, every rank compares on
            # restore), and a restore under different rank geometry must
            # fail fast naming "partition"
            "num_chunks": int(
                self.partition.num_chunks if self.partition is not None
                else self.source.num_chunks
            ),
            "chunk_rows": int(
                self.partition.chunk_rows if self.partition is not None
                else self.source.chunk_rows
            ),
            "total_records": int(
                self.partition.total_records if self.partition is not None
                else self.source.total_records
            ),
            "partition": (
                None if self.partition is None else {
                    "num_ranks": int(self.partition.num_ranks),
                    "chunk_ranges": [
                        list(r) for r in self.partition.chunk_ranges
                    ],
                    "plan": self.partition.fingerprint,
                }
            ),
            # input IDENTITY, not just geometry: a daily re-run against
            # regenerated data of the same shape must fail fast, never
            # resume the old run's state (file-backed sources only)
            "input": (
                None if getattr(self.source, "files", None) is None
                else [
                    [os.path.basename(f), int(os.path.getsize(f))]
                    for f in self.source.files
                ]
            ),
            **sched,
        }

    def _restore(self, checkpointer, fingerprint, step=None):
        ckpt = checkpointer.restore(step=step)
        if ckpt is None:
            return None
        if ckpt.meta.get("kind") != "game_streaming":
            raise ValueError(
                f"checkpoint at {checkpointer.directory} is not a streamed-"
                f"GAME checkpoint (kind={ckpt.meta.get('kind')!r}); use a "
                "fresh checkpoint directory"
            )
        mismatch = fingerprint_mismatch(
            ckpt.meta.get("fingerprint"), fingerprint
        )
        if mismatch is not None:
            raise ValueError(
                f"streamed-GAME checkpoint at {checkpointer.directory} was "
                f"written under a different run fingerprint ({mismatch}); "
                "resume with the original chunk plan/schedule/optimizers, "
                "or use a fresh checkpoint directory"
            )
        state = GameTrainState(
            fe_coefficients=jnp.asarray(ckpt.arrays["fe_coefficients"]),
            re_tables={
                k[len("re_tables/"):]: jnp.asarray(v)
                for k, v in ckpt.arrays.items()
                if k.startswith("re_tables/")
            },
        )
        return ckpt, state

    # -- entry point ----------------------------------------------------------

    def train(
        self,
        *,
        num_sweeps: int,
        state: GameTrainState | None = None,
        tolerance: float = 0.0,
        checkpointer=None,
        checkpoint_every: int = 1,
        resume: bool = True,
        resume_step: "int | None" = None,
        on_sweep=None,
    ) -> StreamingGameResult:
        """Run up to ``num_sweeps`` streamed CD sweeps.

        on_sweep: optional observer ``(sweep_done, num_sweeps, loss)``
        called at the end of every sweep (the driver wires the journal
        heartbeat through it — ISSUE 12); observe-only.

        tolerance > 0 adds a loss-plateau stop: the run ends early when a
        sweep's relative training-loss decrease falls below it (the
        epochs-to-tolerance criterion the DuHL-vs-uniform comparison
        measures). ``checkpointer``: optional
        ``io.checkpoint.TrainingCheckpointer`` — sweep-granular commits
        through the exchange-consistent helper; a restored run recomputes
        its scores from the saved tables through the same jitted steps
        that produced them and continues bitwise. ``resume_step`` pins
        the restore to ONE published step (ISSUE 15's coordinated
        rollback; 0 = restart from scratch, None = newest intact).
        """
        if self.schedule is None:
            self.schedule = UniformChunkSchedule(self._num_chunks_global)
        fingerprint = self._fingerprint()
        start_sweep = 0
        losses: list[float] = []
        if resume_step == 0:
            resume = False
        if checkpointer is not None and resume and state is None:
            restored = self._restore(
                checkpointer, fingerprint,
                step=resume_step if resume_step else None,
            )
            if restored is not None:
                ckpt, state = restored
                start_sweep = min(int(ckpt.step), num_sweeps)
                losses = [float(x) for x in ckpt.meta.get("losses", [])]
                losses = losses[:start_sweep]
                self.schedule.load_state(ckpt.meta["schedule_state"])
                from photon_ml_tpu.telemetry import resilience_counters

                resilience_counters.record_checkpoint_restore()
                logger.info(
                    "resuming streamed GAME from checkpoint sweep %d",
                    start_sweep,
                )
        fresh_state = state is None
        if fresh_state:
            state = self.init_state()
        scores = (
            self._zero_scores() if fresh_state
            else self.refresh_all_scores(state)
        )
        chunk_visits = 0
        for sweep in range(start_sweep, num_sweeps):
            # the schedule plans in GLOBAL chunk ids (identical state on
            # every rank — the DuHL working set is a pure function of the
            # allgathered signal); each rank executes only its own range,
            # converted to local ids (identity when unpartitioned)
            self._cache.set_pinned({
                g - self._chunk_lo for g in self.schedule.pinned()
                if self._chunk_lo <= g < self._chunk_hi
            })
            visit = [
                g - self._chunk_lo for g in self.schedule.plan_sweep()
                if self._chunk_lo <= g < self._chunk_hi
            ]
            chunk_visits += len(visit) * len(self.re_specs)
            with tracing.span("stream_game/sweep", cat="stream",
                              sweep=sweep, chunks=len(visit)):
                state = self._sweep(
                    state, scores, visit,
                    final_sweep=(sweep + 1 == num_sweeps),
                )
            self.schedule.sweep_done()
            losses.append(self._weighted_loss(scores))
            if not np.isfinite(losses[-1]):
                from photon_ml_tpu.io.checkpoint import DivergenceError

                raise DivergenceError(
                    f"streamed GAME sweep {sweep} produced non-finite loss"
                    + (
                        f"; last good checkpoint: step "
                        f"{checkpointer.latest_step()} in "
                        f"{checkpointer.directory}"
                        if checkpointer is not None else ""
                    )
                )
            if checkpointer is not None and (
                (sweep + 1) % max(1, checkpoint_every) == 0
                or sweep + 1 == num_sweeps
            ):
                arrays = {
                    "fe_coefficients": np.asarray(
                        jax.device_get(state.fe_coefficients)
                    ),
                    **{
                        f"re_tables/{k}": np.asarray(jax.device_get(v))
                        for k, v in state.re_tables.items()
                    },
                }
                commit_checkpoint(
                    checkpointer, sweep + 1, arrays,
                    {
                        "kind": "game_streaming",
                        "fingerprint": fingerprint,
                        "losses": losses,
                        "schedule_state": self.schedule.state_dict(),
                    },
                    exchange=self.exchange,
                )
            if on_sweep is not None:
                on_sweep(sweep + 1, num_sweeps, losses[-1])
            if (
                tolerance > 0.0 and len(losses) >= 2
                and abs(losses[-2] - losses[-1])
                <= tolerance * max(abs(losses[-2]), 1e-12)
            ):
                logger.info(
                    "streamed GAME reached loss plateau at sweep %d", sweep
                )
                break
        # sweeps THIS invocation ran (restored sweeps are excluded, like
        # chunk_loads/chunk_visits — per-sweep divisions of the evidence
        # stay consistent across resumes; the full loss history still
        # rides `losses`)
        sweeps_run = len(losses) - start_sweep
        stream_counters.set_game_stream_evidence(
            chunk_loads=self._cache.loads,
            chunk_visits=chunk_visits,
            sweeps=sweeps_run,
        )
        return StreamingGameResult(
            state=state,
            losses=losses,
            sweeps=sweeps_run,
            chunk_loads=self._cache.loads,
            chunk_visits=chunk_visits,
        )


# ---------------------------------------------------------------------------
# Streamed validation scoring (ISSUE 17 rider)
# ---------------------------------------------------------------------------


def score_game_stream(
    state: GameTrainState,
    source,
    task: TaskType,
    fe_feature_shard_id: str,
    re_feature_shards: "Mapping[str, str]",
    *,
    prefetch: bool = True,
    retry_policy=None,
    chunk_timeout: float = DEFAULT_CHUNK_TIMEOUT,
    return_scalars: bool = False,
) -> np.ndarray:
    """Score a held-out dataset chunk-wise against a streamed GAME model —
    the out-of-core twin of ``GameModel.score_dataset(ds) + ds.offsets``
    (the driver's validation semantics, estimators.GameEstimator.fit):
    per chunk, the FE margin plus every RE coordinate's score plus the
    chunk's offsets, scattered into an [n] host vector through the SAME
    module-level jitted steps the training sweeps use (so the streamed
    scores match the in-core path to float round-off; O(n·d) features only
    ever exist one chunk at a time). The validation source must be built
    with the TRAINING index maps and entity vocabs — entities unseen in
    training carry index -1 and score 0, exactly like the in-core build.

    ``re_feature_shards`` maps each RE type in ``state.re_tables`` to the
    feature shard its coordinate scores (RandomEffectStepSpec
    .feature_shard_id). Single-rank: each rank scores only the chunks its
    source holds. ``return_scalars=True`` additionally returns the [n]
    evaluation scalars ({labels, offsets, weights}) collected from the
    same decode pass — what a validation evaluator needs, without a
    second pass over the input.
    """
    missing = [t for t in state.re_tables if t not in re_feature_shards]
    if missing:
        raise ValueError(
            f"re_feature_shards is missing shard assignments for {missing}"
        )
    objective = GLMObjective(loss_for_task(task), 0.0, use_pallas=False)
    n = source.total_records
    dtype = solve_dtype_of(np.dtype(source.dtype))
    scores = np.zeros(n, dtype)
    scalars = (
        {k: np.zeros(n, dtype) for k in ("labels", "offsets", "weights")}
        if return_scalars else None
    )
    starts = getattr(source, "record_starts", None)
    with tracing.span("stream_game/score", cat="stream",
                      chunks=source.num_chunks):
        with ChunkPrefetcher(
            source, prefetch=prefetch, retry_policy=retry_policy,
            chunk_timeout=chunk_timeout,
        ) as chunks:
            for spec, chunk in zip(source.specs, chunks):
                m = chunk.num_records
                total = np.asarray(_fe_margin_chunk(
                    state.fe_coefficients,
                    {"features": chunk.features[fe_feature_shard_id]},
                    objective=objective,
                ), dtype)
                for re_type, table in state.re_tables.items():
                    batch = {
                        "features":
                            chunk.features[re_feature_shards[re_type]],
                        "entity_idx": chunk.entity_idx[re_type],
                    }
                    total = total + np.asarray(
                        _re_score_chunk(table, batch), dtype
                    )
                total = total + np.asarray(chunk.offsets, dtype)
                if getattr(chunk, "rows", None) is not None:
                    rows = np.asarray(chunk.rows[:m])
                elif starts is not None:
                    rows = np.arange(starts[spec.index],
                                     starts[spec.index] + m)
                else:
                    raise ValueError(
                        "the validation chunk source carries neither row "
                        "ids nor record starts — scores cannot be placed"
                    )
                scores[rows] = total[:m]
                if scalars is not None:
                    scalars["labels"][rows] = chunk.labels[:m]
                    scalars["offsets"][rows] = chunk.offsets[:m]
                    scalars["weights"][rows] = chunk.weights[:m]
    if return_scalars:
        return scores, scalars
    return scores
