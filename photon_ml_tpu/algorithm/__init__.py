from photon_ml_tpu.algorithm.coordinates import (  # noqa: F401
    Coordinate,
    CoordinateOptimizationConfig,
    FixedEffectCoordinate,
    ModelCoordinate,
    RandomEffectCoordinate,
)
from photon_ml_tpu.algorithm.coordinate_descent import (  # noqa: F401
    CoordinateDescentResult,
    run_coordinate_descent,
)
from photon_ml_tpu.algorithm.mf_coordinate import (  # noqa: F401
    MatrixFactorizationCoordinate,
    MFDataset,
    build_mf_dataset,
)
