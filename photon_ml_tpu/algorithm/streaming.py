"""Out-of-core streaming epochs: exact chunked GLM objectives.

Reference parity: photon-lib function/glm/DistributedGLMLossFunction.scala
:91-135 — the reference computes value/gradient/Hessian-vector as an
``RDD.treeAggregate`` over partitions that never co-reside in one
machine's memory; ValueAndGradientAggregator.scala /
HessianVectorAggregator.scala are its per-partition seqOps. This module is
the TPU-native equivalent for n beyond device memory: a GLM objective is
a SUM over samples, so one epoch over fixed-shape chunks accumulates the
EXACT value/gradient/Hv (not a stochastic estimate), with host decode of
chunk k+1 double-buffered behind device compute of chunk k
(io/stream_reader.ChunkPrefetcher — the Snap ML compute/ingest overlap,
arXiv:1803.06333).

The 413 rule, mechanized: every chunk enters the device through the
ARGUMENT list of the ONE module-level jitted step (never a closed-over
constant — closed-over batches serialize into the remote-compile request
and blow the tunnel's HTTP limit at ~250 MB, the landmine that cost a
whole round), and the accumulator is carry-threaded through that step so
XLA cannot hoist the per-chunk work. dev/lint_parity.py check 9
statically bans nested ``jax.jit`` in the streaming modules to keep it
that way.

Solvers: LBFGS/OWLQN/TRON consume the accumulated (value, grad, Hv)
through their ``host_loop=True`` mode (optim/common.run_while) — the
IDENTICAL per-iteration body math as the in-core solve, driven from
Python so each objective evaluation can be an epoch. The streamed final
loss/coefficients therefore match the in-core solver to float round-off
(the only difference is the chunked summation order).

Multi-process composition: with a ``MetadataExchange``, each rank streams
only its own block assignment (io/stream_reader.plan_chunks block_subset)
and the per-rank data-part accumulators are summed IN RANK ORDER through
the exchange at every epoch end — deterministic, identical on every rank,
riding the exchange's rank-attributed deadlines. Regularization is added
once, after the cross-rank sum.
"""

from __future__ import annotations

import base64
from functools import partial
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.io.stream_reader import (
    DEFAULT_CHUNK_TIMEOUT,
    ChunkPrefetcher,
    ChunkSource,
)
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.ops.sparse_objective import SparseGLMObjective
from photon_ml_tpu.telemetry import tracing
from photon_ml_tpu.telemetry.program_ledger import ledger_jit

Array = jax.Array


# ---------------------------------------------------------------------------
# The one jit signature chunks ride (module scope — lint check 9)
# ---------------------------------------------------------------------------


@partial(ledger_jit, label="streaming/accumulate_value_grad",
         static_argnames=("objective",))
def _accumulate_value_grad(acc_value, acc_grad, coefficients, batch, *, objective):
    """acc += chunk's DATA value/gradient (no regularization — that is
    added once per epoch, after any cross-rank sum). The accumulators are
    the carry; the chunk batch is an argument."""
    value, grad = objective.value_and_gradient(coefficients, batch)
    return acc_value + value, acc_grad + grad


@partial(ledger_jit, label="streaming/accumulate_hessian_vector",
         static_argnames=("objective",))
def _accumulate_hessian_vector(acc_hv, coefficients, vector, batch, *, objective):
    """acc += chunk's DATA Hessian-vector product (TRON's CG inner loop)."""
    return acc_hv + objective.hessian_vector(coefficients, vector, batch)


def _pack_f64(a: np.ndarray) -> str:
    return base64.b64encode(
        np.ascontiguousarray(a, dtype="<f8").tobytes()
    ).decode("ascii")


def _unpack_f64(s: str) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype="<f8")


def _pack_i64(a: np.ndarray) -> str:
    return base64.b64encode(
        np.ascontiguousarray(a, dtype="<i8").tobytes()
    ).decode("ascii")


def _unpack_i64(s: str) -> np.ndarray:
    return np.frombuffer(base64.b64decode(s), dtype="<i8")


class StreamingGLMObjective:
    """A GLM objective whose every evaluation is one chunked epoch.

    Quacks like ``ops.objective.BoundObjective`` (value / value_and_grad /
    hessian_vector) so ``optim.optimizer.solve(..., host_loop=True)``
    drives it directly; ``.objective`` exposes the underlying per-chunk
    dense/sparse objective (solve()'s loss introspection reads it).

    l2_weight lives HERE, not in the chunk objective: the chunk steps
    accumulate the data part only, and the epoch finalizer adds
    ``(l2/2)‖w‖²`` / ``l2·w`` / ``l2·v`` exactly once — after the
    cross-rank sum when an exchange is attached.

    mesh: optional device mesh — dense chunk batches are placed sharded
    along the sample axis (first mesh axis) before accumulation, so the
    chunked epoch reduces across devices exactly like the in-core sharded
    objective (the 1-vs-8 invariance tests pin it).
    """

    def __init__(
        self,
        source: ChunkSource,
        loss,
        *,
        l2_weight: float = 0.0,
        normalization=None,
        mesh=None,
        exchange=None,
        prefetch: bool = True,
        retry_policy=None,
        chunk_timeout: float = DEFAULT_CHUNK_TIMEOUT,
    ):
        self.source = source
        self.l2_weight = float(l2_weight)
        if source.sparse:
            self.objective = SparseGLMObjective(
                loss, 0.0, normalization=normalization
            )
        else:
            self.objective = GLMObjective(loss, 0.0, normalization=normalization)
        self.mesh = mesh
        self.exchange = exchange
        self.prefetch = bool(prefetch)
        self.retry_policy = retry_policy
        self.chunk_timeout = float(chunk_timeout)
        #: epochs run so far (one per objective evaluation) — journal fodder
        self.epochs = 0

    # -- epoch machinery -----------------------------------------------------

    def _prefetcher(self) -> ChunkPrefetcher:
        return ChunkPrefetcher(
            self.source,
            prefetch=self.prefetch,
            retry_policy=self.retry_policy,
            chunk_timeout=self.chunk_timeout,
        )

    def _place(self, batch):
        if self.mesh is None or self.source.sparse:
            return batch
        from jax.sharding import NamedSharding, PartitionSpec

        axis = self.mesh.axis_names[0]
        row = NamedSharding(self.mesh, PartitionSpec(axis))
        row2d = NamedSharding(self.mesh, PartitionSpec(axis, None))
        shardings = type(batch)(
            features=row2d, labels=row, offsets=row, weights=row
        )
        return jax.device_put(batch, shardings)

    def _epoch(self, fold: Callable, carry):
        # host wall-clock spans only: the accumulate step DISPATCHES
        # asynchronously, so its span measures the host-blocking portion
        # (transfer + dispatch), not device time — exactly the overlap
        # seam the prefetcher's decode/wait spans complement
        with tracing.span("stream/epoch", cat="stream", epoch=self.epochs,
                          chunks=self.source.num_chunks):
            with self._prefetcher() as chunks:
                for i, batch in enumerate(chunks):
                    with tracing.span("stream/accumulate", cat="stream",
                                      chunk=i):
                        carry = fold(carry, self._place(batch))
        self.epochs += 1
        return carry

    def _cross_rank_sum(self, arrays: Sequence[Array]) -> list[np.ndarray]:
        """Sum model-sized accumulators across ranks IN RANK ORDER via the
        metadata exchange (deterministic: every rank computes the identical
        f64 sum). Model-sized payloads only — the [n] sample axis never
        crosses this channel."""
        shapes = [np.asarray(a).shape for a in arrays]
        flat = np.concatenate(
            [np.asarray(a, dtype=np.float64).ravel() for a in arrays]
        )
        gathered = self.exchange.allgather(
            "stream_accumulator", {"acc": _pack_f64(flat)}
        )
        total = np.zeros_like(flat)
        for g in gathered:  # rank order — the exchange contract
            total = total + _unpack_f64(g["acc"])
        out, pos = [], 0
        for shape in shapes:
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            out.append(total[pos:pos + size].reshape(shape))
            pos += size
        return out

    # -- BoundObjective protocol ---------------------------------------------

    def value_and_grad(self, w: Array) -> tuple[Array, Array]:
        w = jnp.asarray(w)
        init = (jnp.zeros((), w.dtype), jnp.zeros_like(w))
        acc_f, acc_g = self._epoch(
            lambda carry, batch: _accumulate_value_grad(
                carry[0], carry[1], w, batch, objective=self.objective
            ),
            init,
        )
        if self.exchange is not None and self.exchange.num_ranks > 1:
            f_np, g_np = self._cross_rank_sum([acc_f, acc_g])
            acc_f = jnp.asarray(f_np, w.dtype).reshape(())
            acc_g = jnp.asarray(g_np, w.dtype)
        if self.l2_weight > 0.0:
            acc_f = acc_f + 0.5 * self.l2_weight * jnp.vdot(w, w)
            acc_g = acc_g + self.l2_weight * w
        return acc_f, acc_g

    def value(self, w: Array) -> Array:
        return self.value_and_grad(w)[0]

    def hessian_vector(self, w: Array, v: Array) -> Array:
        w = jnp.asarray(w)
        v = jnp.asarray(v)
        acc = self._epoch(
            lambda carry, batch: _accumulate_hessian_vector(
                carry, w, v, batch, objective=self.objective
            ),
            jnp.zeros_like(w),
        )
        if self.exchange is not None and self.exchange.num_ranks > 1:
            (hv_np,) = self._cross_rank_sum([acc])
            acc = jnp.asarray(hv_np, w.dtype)
        if self.l2_weight > 0.0:
            acc = acc + self.l2_weight * v
        return acc


def streaming_summarize(
    source: ChunkSource,
    *,
    prefetch: bool = True,
    retry_policy=None,
    chunk_timeout: float = DEFAULT_CHUNK_TIMEOUT,
) -> dict:
    """Weighted feature statistics from one streaming pass — the chunked
    equivalent of ``data.batch.summarize`` (reference
    stat/BasicStatisticalSummary.scala) for normalization contexts over
    data that never materializes in core. Accumulates f64 weighted sums
    (Σw, Σwx, Σwx², max|x|) host-side; zero-weight chunk padding
    contributes nothing, so the mean/variance/max_magnitude match the
    in-core summary to f64 round-off. Dense sources only."""
    if source.sparse:
        raise ValueError(
            "streaming_summarize covers dense sources; sparse shards keep "
            "their own summary path (data.sparse_batch.summarize_sparse)"
        )
    wsum = 0.0
    count = 0
    sum_wx = np.zeros((source.dim,), np.float64)
    sum_wxx = np.zeros((source.dim,), np.float64)
    max_mag = np.zeros((source.dim,), np.float64)
    with ChunkPrefetcher(
        source, prefetch=prefetch, retry_policy=retry_policy,
        chunk_timeout=chunk_timeout,
    ) as chunks:
        for batch in chunks:
            x = np.asarray(batch.features, dtype=np.float64)
            w = np.asarray(batch.weights, dtype=np.float64)
            wsum += float(w.sum())
            count += int((w != 0).sum())
            sum_wx += w @ x
            sum_wxx += w @ (x * x)
            max_mag = np.maximum(max_mag, np.abs(x).max(axis=0))
    if wsum <= 0.0:
        raise ValueError("streaming_summarize saw no positive-weight samples")
    mean = sum_wx / wsum
    # Σw(x-m)² = Σwx² - 2mΣwx + m²Σw, over wsum-1 like the in-core summary
    var = (sum_wxx - 2.0 * mean * sum_wx + mean * mean * wsum) / max(
        wsum - 1.0, 1.0
    )
    return {
        "count": count,
        "weight_sum": wsum,
        "mean": mean,
        "variance": np.maximum(var, 0.0),
        "max_magnitude": max_mag,
    }
