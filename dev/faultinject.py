#!/usr/bin/env python
"""Fault-injection harness for the resilience layer's chaos suite.

Context-manager/callable injectors that manufacture the failure classes
ISSUE 3 names — each maps to a recovery path the chaos tests
(tests/test_resilience.py) drive end to end on the virtual CPU mesh:

- :func:`flaky` / :class:`FlakyCallable` — fails N times then succeeds
  (the transient-tunnel shape; exercises RetryPolicy).
- :func:`truncate_avro_block` / :func:`corrupt_avro_block` /
  :func:`break_avro_sync` — in-place container damage (exercises the
  quarantine readers in io/avro.py).
- :func:`crash_before_replace` — raises between the checkpoint's temp-dir
  write and its ``os.replace`` publish (exercises save atomicity).
- :func:`corrupt_checkpoint_step` — truncates a saved ``step_*`` dir's
  files (exercises restore's newest-intact-step fallback).
- :class:`WithholdingExchange` — a MetadataExchange wrapper whose rank
  never publishes selected tags (exercises ExchangeTimeout attribution).
- :func:`die_at_barrier` / :class:`BarrierKiller` — a rank-targeted kill:
  withhold the matching exchange op, then raise a classified-transient
  preemption in THAT rank only (exercises peer-abort attribution +
  coordinated rollback; ISSUE 15). ``times=None`` makes the rank FLAP
  (dies every attempt — exercises shared-budget exhaustion).
- :func:`abort_marker_corruptor` — garbles every abort marker a rank
  posts (exercises the unattributed-but-bounded PeerAbort path).
- :func:`poison_coordinate_updates` — NaN-poisons the first K model
  updates of one coordinate class (exercises DivergenceError +
  checkpoint-restore recovery).
- :func:`crash_after_chunks` — kills the run mid-streaming-epoch after N
  accumulated chunk decodes (exercises SolverCheckpointer resume through
  run_with_recovery; ISSUE 8).
- :func:`preempt_after_calls` / :func:`device_loss_error` — a simulated
  pool preemption: a classified-transient device-loss error after N
  jitted steps of any method (exercises preemption classification +
  exchange-consistent partitioned checkpoint resume; ISSUE 8).

Dev-tooling, not shipped API: lives next to dev/lint_parity.py and is
imported only by tests.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Callable


class InjectedCrash(RuntimeError):
    """The harness's stand-in for a hard process death at a chosen point."""


@dataclasses.dataclass
class FlakyCallable:
    """Calls ``fn`` but raises ``exc_factory()`` for the first
    ``failures`` invocations — the flaky-then-succeeding callable."""

    fn: Callable
    failures: int
    exc_factory: Callable[[], BaseException] = ConnectionError
    calls: int = 0

    def __call__(self, *args, **kwargs):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc_factory()
        return self.fn(*args, **kwargs)


def flaky(failures: int, exc_factory=ConnectionError, result=None):
    """A FlakyCallable returning ``result`` once the failures run out."""
    return FlakyCallable(fn=lambda: result, failures=failures,
                         exc_factory=exc_factory)


# ---------------------------------------------------------------------------
# Avro container damage (in place, on a copy the test owns)
# ---------------------------------------------------------------------------


def _block_span(path: str | os.PathLike, block: int) -> tuple[int, int, int]:
    """(payload_offset, payload_size, record_count) of block ``block``."""
    from photon_ml_tpu.io.avro import scan_block_index

    index = scan_block_index(path)
    n_records, size, offset = index[block]
    return offset, size, n_records


def truncate_avro_block(path: str | os.PathLike, block: int = -1) -> None:
    """Cut the file mid-way through ``block``'s payload (default: last
    block) — the torn-write / partial-copy shape."""
    from photon_ml_tpu.io.avro import scan_block_index

    index = scan_block_index(path)
    offset, size, _ = _block_span(path, block % len(index))
    with open(path, "r+b") as f:
        f.truncate(offset + max(size // 2, 1))


def corrupt_avro_block(path: str | os.PathLike, block: int = 0,
                       nbytes: int = 8) -> None:
    """Overwrite the first ``nbytes`` of ``block``'s payload with 0xFF —
    bit-rot inside an intact frame (framing/sync stay valid)."""
    offset, size, _ = _block_span(path, block)
    with open(path, "r+b") as f:
        f.seek(offset)
        f.write(b"\xff" * min(nbytes, size))


def break_avro_sync(path: str | os.PathLike, block: int = 0) -> None:
    """Destroy the 16-byte sync marker TRAILING ``block`` — the following
    block becomes unreachable (resync skips to the next intact marker)."""
    offset, size, _ = _block_span(path, block)
    with open(path, "r+b") as f:
        f.seek(offset + size)
        f.write(b"\xaa" * 16)


# ---------------------------------------------------------------------------
# Checkpoint damage
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def crash_before_replace():
    """Patch ``os.replace`` to raise InjectedCrash — the save dies after
    its temp-dir write, before the atomic publish (the window the
    checkpointer's atomicity contract covers). Module-global patch;
    restore is guaranteed on exit."""
    real = os.replace

    def boom(*args, **kwargs):
        raise InjectedCrash(
            "injected crash between temp-dir write and os.replace"
        )

    os.replace = boom
    try:
        yield
    finally:
        os.replace = real


def corrupt_checkpoint_step(directory: str | os.PathLike, step: int,
                            target: str = "arrays.npz") -> None:
    """Truncate ``step_<k>/<target>`` to half — external damage to a
    PUBLISHED checkpoint (the atomic save never produces this; a torn
    disk/copy does)."""
    path = os.path.join(str(directory), f"step_{step:08d}", target)
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.truncate(max(size // 2, 1))


# ---------------------------------------------------------------------------
# Simulated preemption / mid-epoch crash (ISSUE 8)
# ---------------------------------------------------------------------------


def device_loss_error() -> RuntimeError:
    """The device-loss shape a preemptible pool produces: jaxlib surfaces
    it as a RuntimeError (XlaRuntimeError) whose TYPE carries no signal —
    only the message does. Classified TRANSIENT (restart-worthy) and
    ``resilience.errors.is_preemption``-positive."""
    return RuntimeError(
        "INTERNAL: TPU device lost: worker preempted by the pool "
        "scheduler; Socket closed"
    )


@contextlib.contextmanager
def crash_after_chunks(n: int, exc_factory=device_loss_error):
    """Kill the streaming pipeline after its ``n``-th successful chunk
    decode — the mid-epoch crash of a preemptible run. Patches
    ``ChunkPrefetcher._load_timed`` (below the retry policy, so the error
    surfaces undamped); fires ONCE, so a restarted attempt heals — the
    resume-skips-completed-work assertion is then meaningful. Yields the
    counter dict (tests assert ``fired`` to prove the crash happened)."""
    from photon_ml_tpu.io.stream_reader import ChunkPrefetcher

    real = ChunkPrefetcher._load_timed
    state = {"loads": 0, "fired": False}

    def wrapped(self, spec):
        state["loads"] += 1
        if not state["fired"] and state["loads"] > n:
            state["fired"] = True
            raise exc_factory()
        return real(self, spec)

    ChunkPrefetcher._load_timed = wrapped
    try:
        yield state
    finally:
        ChunkPrefetcher._load_timed = real


@contextlib.contextmanager
def preempt_after_calls(obj, method: str, n: int,
                        exc_factory=device_loss_error):
    """Simulated pool preemption: patch ``obj.method`` (a class or an
    instance — e.g. ``GameTrainProgram.step``, the fused sweep's jitted
    step) to raise a classified-transient device-loss error after ``n``
    successful calls. Fires ONCE (the preempted worker comes back), so a
    recovery restart completes. Yields the counter dict."""
    real = getattr(obj, method)
    state = {"calls": 0, "fired": False}

    def wrapped(*args, **kwargs):
        state["calls"] += 1
        if not state["fired"] and state["calls"] > n:
            state["fired"] = True
            raise exc_factory()
        return real(*args, **kwargs)

    setattr(obj, method, wrapped)
    try:
        yield state
    finally:
        setattr(obj, method, real)


# ---------------------------------------------------------------------------
# Exchange withholding
# ---------------------------------------------------------------------------


class WithholdingExchange:
    """Wraps a MetadataExchange; this rank never publishes (never calls)
    exchanges whose tag contains any of ``withhold`` — simulating a rank
    that crashed or skipped a collective. The OTHER ranks' deadline then
    fires a rank-attributed ExchangeTimeout naming this rank."""

    def __init__(self, inner, withhold: tuple[str, ...]):
        self._inner = inner
        self._withhold = tuple(withhold)
        self.rank = inner.rank
        self.num_ranks = inner.num_ranks

    def _withheld(self, tag: str) -> bool:
        return any(w in tag for w in self._withhold)

    def allgather(self, tag: str, payload) -> list:
        if self._withheld(tag):
            raise InjectedCrash(
                f"rank {self.rank} withheld allgather {tag!r}"
            )
        return self._inner.allgather(tag, payload)

    def barrier(self, tag: str) -> None:
        if self._withheld(tag):
            raise InjectedCrash(
                f"rank {self.rank} withheld barrier {tag!r}"
            )
        return self._inner.barrier(tag)


# ---------------------------------------------------------------------------
# Rank-targeted kills + abort-marker damage (ISSUE 15 coordinated recovery)
# ---------------------------------------------------------------------------


class BarrierKiller:
    """Wraps a MetadataExchange: when THIS wrapper's rank is the targeted
    rank and an exchange op's tag contains ``tag``, the op is WITHHELD
    (never reaches the transport — the rank's key/barrier arrival simply
    never happens) and ``exc_factory()`` is raised in that rank — the
    withhold-then-raise-preemption shape of a pool reclaiming one worker
    mid-protocol. Other ranks and other tags pass through untouched.

    ``times=1`` (default) fires once, so the coordinated rollback's next
    attempt heals — the resume-bitwise assertion is then meaningful.
    ``times=None`` makes the rank FLAP (dies at the same tag every
    attempt) — the shared-restart-budget exhaustion fixture.

    The coordinator-facing surface (``set_generation`` / ``post_abort`` /
    ``pending_abort`` / ``generation``) passes through, so a killed rank's
    ``run_with_recovery(coordinator=...)`` path works unmodified.
    """

    def __init__(self, inner, tag: str, rank: int, *, times: "int | None" = 1,
                 exc_factory: Callable[[], BaseException] = None):
        self._inner = inner
        self._tag = str(tag)
        self._target = int(rank)
        self._times = times
        self._exc_factory = exc_factory or device_loss_error
        self.state = {"fired": 0}

    @property
    def rank(self) -> int:
        return self._inner.rank

    @property
    def num_ranks(self) -> int:
        return self._inner.num_ranks

    @property
    def generation(self):
        return self._inner.generation

    def set_generation(self, generation: int) -> None:
        self._inner.set_generation(generation)

    def post_abort(self, info) -> None:
        self._inner.post_abort(info)

    def pending_abort(self):
        return self._inner.pending_abort()

    def _maybe_die(self, tag: str) -> None:
        if (
            self._inner.rank == self._target
            and self._tag in tag
            and (self._times is None or self.state["fired"] < self._times)
        ):
            self.state["fired"] += 1
            raise self._exc_factory()

    def allgather(self, tag: str, payload) -> list:
        self._maybe_die(tag)
        return self._inner.allgather(tag, payload)

    def barrier(self, tag: str) -> None:
        self._maybe_die(tag)
        return self._inner.barrier(tag)


def die_at_barrier(exchange, tag: str, rank: int, *,
                   times: "int | None" = 1,
                   exc_factory=None) -> BarrierKiller:
    """Kill ``rank`` at its next exchange op whose tag contains ``tag``:
    the op is withheld and a classified-transient preemption raised in
    that rank only (see :class:`BarrierKiller`). Pass ``times=None`` for
    a flapping rank."""
    return BarrierKiller(exchange, tag, rank, times=times,
                         exc_factory=exc_factory)


@contextlib.contextmanager
def abort_marker_corruptor(exchange):
    """Patch ``exchange.post_abort`` so every marker this rank writes is
    garbled bytes-of-a-string instead of the attributed dict — the torn-
    write shape. Peers must STILL fail bounded and typed (a PeerAbort
    with ``origin_rank=None`` naming the unparseable marker), never hang
    out the deadline. Yields a counter dict (``posted``)."""
    real = exchange.post_abort
    state = {"posted": 0}

    def corrupted(info):
        state["posted"] += 1
        real("\xff\x00 corrupt abort marker (injected)")

    exchange.post_abort = corrupted
    try:
        yield state
    finally:
        exchange.post_abort = real


# ---------------------------------------------------------------------------
# NaN poisoning
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def poison_coordinate_updates(coordinate_cls, times: int = 1):
    """Patch ``coordinate_cls.update_model`` so its first ``times`` calls
    return a NaN-poisoned model — a diverged-lane stand-in the CD loop's
    finite check must catch as DivergenceError. Subsequent calls behave
    normally (so a checkpoint-restore retry succeeds)."""
    import numpy as np

    real = coordinate_cls.update_model
    state = {"remaining": int(times)}

    def poisoned(self, model, partial_scores):
        out_model, info = real(self, model, partial_scores)
        if state["remaining"] > 0:
            state["remaining"] -= 1
            poisoned_model = _nan_poison_model(out_model, np)
            return poisoned_model, info
        return out_model, info

    coordinate_cls.update_model = poisoned
    try:
        yield state
    finally:
        coordinate_cls.update_model = real


def _nan_poison_model(model, np):
    """A copy of ``model`` with its leading coefficient array set to NaN
    (enough for the coordinate's re-score to go non-finite)."""
    import dataclasses as dc

    from photon_ml_tpu.models.game import FixedEffectModel, RandomEffectModel

    if isinstance(model, FixedEffectModel):
        coeffs = model.glm.coefficients
        means = np.full_like(np.asarray(coeffs.means), np.nan)
        return dc.replace(
            model,
            glm=dc.replace(
                model.glm, coefficients=dc.replace(coeffs, means=means)
            ),
        )
    if isinstance(model, RandomEffectModel):
        poisoned = np.full_like(np.asarray(model.coefficients), np.nan)
        return dc.replace(model, coefficients=poisoned)
    raise TypeError(f"cannot NaN-poison model type {type(model)!r}")
