#!/usr/bin/env python
"""Run doctor: one offline findings report over a run directory's evidence.

Takes a directory holding any mix of driver bench artifacts
(``BENCH_r*.json`` / ``MULTICHIP_r*.json``), the ``bench-report.json``
sidecar, JSONL run journals (``run-journal.jsonl`` and friends — with
``--live`` also their crash-durable ``.partial`` stage files), and per-rank
``trace-*.json`` files, and emits ONE report:

- a verdict per bench row (telemetry/verdicts.py — the BASELINE.md same-run
  win criteria as code), with known pathology signatures named with their
  measured causes (negative marginals, ~40x contention blowouts,
  ``parsed: null`` tail overruns);
- cross-round history findings (improvements, plateaus) in each rule's
  declared direction;
- registry-counter cross-checks from the journal snapshot
  (overlap_fraction ~ 0 with prefetch on, high serve pad fraction,
  quarantined blocks, preemption restarts, exhausted restart budgets) plus
  the last heartbeat cursor and failure rows of a crashed/in-flight run;
- the per-program compiled-program ledger table (ISSUE 13): per labeled
  jit program — calls, compiles, recompiles, signatures, compile seconds,
  flops, peak bytes, with each label's LAST recompile attribution (the
  exact signature leaves that changed), plus heartbeat staleness and
  hbm/compile drift so a wedged run is distinguishable from a slow one;
- the straggler table from the per-rank trace files (dev/trace_summary.py
  machinery — online and offline reports share one implementation);
- the cross-rank coordinated-recovery table (ISSUE 15): per-rank
  restarts/aborts/generations merged over EVERY rank's journal, the
  restart-storm pathology naming a flapping culprit rank, and (with
  ``--live``) the last abort marker seen.

Exit status: nonzero iff the CURRENT round (the sidecar when present, else
the highest BENCH round) contains a row that LOST its registered win
criterion — so "fold the bench results into BASELINE.md" (ROADMAP item 1)
starts from a machine verdict, not from hand-decoding unit strings.
Historical pathologies (the r04/r05 ``parsed: null`` captures) are
reported but only fail under ``--strict``.

Run from the repo root (judges the checked-in history) or point it at a
production run's ``--telemetry-dir``:

    python -m dev.doctor [RUN_DIR] [--live] [--strict] [--json]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from photon_ml_tpu.telemetry import bench_history, verdicts  # noqa: E402
from photon_ml_tpu.telemetry.journal import (  # noqa: E402
    JOURNAL_PARTIAL_SUFFIX as PARTIAL_SUFFIX,
    heartbeat_cursor,
    read_journal,
)

#: journal basenames the doctor looks for (plus their .partial stages)
JOURNAL_GLOB = "*.jsonl"


def _find_journals(directory: str, live: bool) -> list[str]:
    paths = sorted(glob.glob(os.path.join(directory, JOURNAL_GLOB)))
    if live:
        finalized = {os.path.basename(p) for p in paths}
        for p in sorted(glob.glob(
            os.path.join(directory, JOURNAL_GLOB + PARTIAL_SUFFIX)
        )):
            # a finalized journal supersedes its own leftover stage file
            if os.path.basename(p)[: -len(PARTIAL_SUFFIX)] not in finalized:
                paths.append(p)
    return paths


def _journal_section(path: str, live: bool) -> tuple[list, list[str], list]:
    """(findings, report lines, parsed records) for one journal file."""
    records = read_journal(path, tolerant=True)
    lines = [f"-- {os.path.basename(path)}: {len(records)} row(s)"]
    findings = verdicts.journal_findings(records)
    if records:
        last = records[-1]
        age = time.time() - float(last.get("ts", time.time()))
        if path.endswith(PARTIAL_SUFFIX) or live:
            lines.append(
                f"   last row: kind={last.get('kind')} seq={last.get('seq')} "
                f"({age:.1f}s ago)"
            )
        heartbeats = [r for r in records if r.get("kind") == "heartbeat"]
        if heartbeats:
            hb = heartbeats[-1]
            lines.append(f"   last heartbeat: {heartbeat_cursor(hb)}")
            if path.endswith(PARTIAL_SUFFIX) or live:
                # staleness is a LIVE signal: a wedged run's newest
                # heartbeat goes stale while a merely slow run's keeps
                # advancing — meaningless for a finalized journal, whose
                # age just says when the run happened
                staleness = time.time() - float(hb.get("ts", time.time()))
                lines.append(
                    f"   heartbeat staleness: {staleness:.1f}s since the "
                    f"newest of {len(heartbeats)} heartbeat(s) "
                    f"(seq {hb.get('seq')})"
                )
                drift = _heartbeat_drift(heartbeats)
                if drift:
                    lines.append(f"   heartbeat drift: {drift}")
    lines.extend(_ledger_table(records))
    return findings, lines, records


def _heartbeat_drift(heartbeats: list) -> str:
    """first -> last movement of the device-memory and compile-count
    snapshots heartbeat rows carry (ISSUE 13): live-HBM drift and a mid-run
    compile storm both show up here before the run ends."""
    first, last = heartbeats[0], heartbeats[-1]
    parts = []
    if first.get("hbm_bytes") is not None or last.get("hbm_bytes") is not None:
        parts.append(
            f"hbm_bytes {first.get('hbm_bytes')} -> {last.get('hbm_bytes')}"
        )
    if first.get("compiles") is not None or last.get("compiles") is not None:
        parts.append(
            f"compiles {first.get('compiles')} -> {last.get('compiles')}"
        )
    return ", ".join(parts)


def _ledger_table(records: list) -> list[str]:
    """The per-program ledger table (ISSUE 13): one row per labeled
    program from the journal's program_compile/program_signature/
    program_recompile rows, with each label's last recompile attribution
    underneath — the 'compile count went up' number next to its cause."""
    per_label: dict[str, dict] = {}
    for r in records:
        kind = r.get("kind")
        if kind not in ("program_compile", "program_signature",
                        "program_recompile"):
            continue
        label = str(r.get("label"))
        ent = per_label.setdefault(label, {
            "compiles": 0, "recompiles": 0, "compile_s": 0.0,
            "flops": None, "peak_bytes": None, "forecast": None,
            "attribution": None,
        })
        if kind == "program_recompile":
            ent["recompiles"] += 1
            ent["attribution"] = r.get("summary")
            continue
        if kind == "program_compile":
            ent["compiles"] += int(r.get("compiles") or 0)
            ent["compile_s"] += float(r.get("compile_seconds") or 0.0)
        cost = r.get("cost") or {}
        if cost.get("flops") is not None:
            ent["flops"] = cost["flops"]
        mem = r.get("memory") or {}
        peak = mem.get("peak_memory_in_bytes", mem.get("temp_size_in_bytes"))
        if peak is not None:
            ent["peak_bytes"] = peak
        if r.get("hbm_forecast_bytes") is not None:
            ent["forecast"] = r["hbm_forecast_bytes"]
    if not per_label:
        return []
    # calls/signatures ride the final metrics snapshot when one was taken
    metrics = next((r for r in reversed(records) if r.get("kind") == "metrics"),
                   None)
    snapshot = (metrics or {}).get("snapshot") or {}
    counters = snapshot.get("counters") or {}
    gauges = snapshot.get("gauges") or {}

    def _fmt(v, unit=""):
        return "-" if v is None else f"{v:g}{unit}"

    lines = [f"   program ledger ({len(per_label)} labeled program(s)):"]
    header = (f"   {'label':<38} {'calls':>6} {'compiles':>8} "
              f"{'recomp':>6} {'sigs':>5} {'compile_s':>9} "
              f"{'flops':>10} {'peak_B':>10} {'fcast_B':>10}")
    lines.append(header)
    for label in sorted(per_label):
        ent = per_label[label]
        calls = counters.get(f"xla/{label}/calls")
        sigs = gauges.get(f"xla/{label}/signatures")
        lines.append(
            f"   {label:<38} {_fmt(calls):>6} {ent['compiles']:>8} "
            f"{ent['recompiles']:>6} {_fmt(sigs):>5} "
            f"{ent['compile_s']:>9.3f} {_fmt(ent['flops']):>10} "
            f"{_fmt(ent['peak_bytes']):>10} {_fmt(ent['forecast']):>10}"
        )
        if ent["attribution"]:
            lines.append(f"      last recompile: {ent['attribution']}")
    return lines


def _trace_section(directory: str) -> list[str]:
    try:
        from dev import trace_summary
    except ImportError:  # running as a loose script next to trace_summary
        import trace_summary  # type: ignore[no-redef]
    files = sorted(glob.glob(os.path.join(directory, "trace-*.json")))
    if not files:
        return []
    events: list[dict] = []
    unreadable: list[str] = []
    for f in files:
        try:
            events.extend(trace_summary.load_trace_events(f))
        except (OSError, ValueError):
            # a SIGKILL'd rank can leave a torn trace file — keep the
            # healthy ranks' evidence, name the torn one
            unreadable.append(os.path.basename(f))
    lines = [f"-- {len(files)} trace file(s), {len(events)} event(s)"]
    if unreadable:
        lines.append(f"   unreadable (torn mid-write?): {unreadable}")
    if events:
        lines.extend(trace_summary.format_report(events, top=5).splitlines())
    return lines


def run_doctor(
    directory: str,
    *,
    live: bool = False,
    strict: bool = False,
) -> tuple[int, list, str]:
    """The doctor's whole pass: returns (exit_code, findings, report_text).

    Importable so tests judge findings structurally; ``main`` wraps it.
    """
    history = bench_history.load_history(directory)
    lines: list[str] = [f"run doctor: {os.path.abspath(directory)}"]
    findings: list = []
    current_round_findings: list = []

    if history.artifacts or history.sidecar is not None:
        lines.append("")
        lines.append("== bench verdicts ==")
        latest = history.latest
        for art in history.artifacts:
            vs = verdicts.judge_artifact(art)
            findings.extend(vs)
            if art is latest:
                current_round_findings.extend(vs)
            for v in vs:
                lines.append(v.line())
        if history.sidecar is not None:
            lines.append(f"-- sidecar {bench_history.SIDECAR_FILENAME} "
                         "(preferred: never tail-truncated)")
            vs = verdicts.judge_artifact(history.sidecar)
            findings.extend(vs)
            current_round_findings.extend(vs)
            for v in vs:
                lines.append(v.line())
        # the CURRENT multichip round gates the exit code like the current
        # bench round does — independently of the sidecar (which never
        # carries multichip evidence)
        current_multi = max(
            (m.round for m in history.multichip if m.round is not None),
            default=None,
        )
        for m in history.multichip:
            v = verdicts.judge_multichip(m)
            findings.append(v)
            if m.round == current_multi:
                current_round_findings.append(v)
            lines.append(v.line())
        hist = verdicts.history_findings(history)
        if hist:
            lines.append("")
            lines.append("== cross-round history ==")
            findings.extend(hist)
            for v in hist:
                lines.append(v.line())
    else:
        lines.append("(no BENCH_r*/MULTICHIP_r* artifacts or sidecar here)")

    journal_paths = _find_journals(directory, live)
    merged_records: list = []
    if journal_paths:
        lines.append("")
        lines.append("== run journals ==")
        for path in journal_paths:
            try:
                jf, jl, records = _journal_section(path, live)
            except OSError as e:
                lines.append(f"-- {path}: unreadable ({e})")
                continue
            merged_records.extend(records)
            findings.extend(jf)
            lines.extend(jl)
            for v in jf:
                lines.append(v.line())

    # coordinated recovery is a CROSS-journal story (ISSUE 15): the
    # per-rank restart table and the restart-storm attribution only make
    # sense over every rank's journal merged
    coord = verdicts.coordination_findings(merged_records)
    if coord:
        lines.append("")
        lines.append("== coordinated recovery ==")
        findings.extend(coord)
        for v in coord:
            lines.append(v.line())
    if live:
        marker = verdicts.last_abort_marker(merged_records)
        if marker is not None:
            lines.append(
                "   last abort marker: "
                f"kind={marker.get('kind')} rank={marker.get('rank')} "
                f"origin_rank={marker.get('origin_rank', marker.get('rank'))} "
                f"generation={marker.get('generation')} "
                f"cause={marker.get('origin_cause', marker.get('cause'))}"
            )

    trace_lines = _trace_section(directory)
    if trace_lines:
        lines.append("")
        lines.append("== traces ==")
        lines.extend(trace_lines)

    regressions = verdicts.regressions(current_round_findings)
    if strict:
        regressions = regressions + [
            v for v in findings
            if v.status in (verdicts.PATHOLOGY, verdicts.WARNING)
        ]
    lines.append("")
    if regressions:
        lines.append(f"REGRESSIONS ({len(regressions)}):")
        for v in regressions:
            lines.append(f"  {v.metric} [{v.rule}]: {v.detail}")
    else:
        lines.append("REGRESSIONS: none")
    return (1 if regressions else 0), findings, "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("directory", nargs="?", default=".",
                   help="run directory (bench artifacts + journals + "
                        "traces); default: cwd")
    p.add_argument("--live", action="store_true",
                   help="also tail crash-durable .partial journal stages "
                        "(a wedged run's evidence before close)")
    p.add_argument("--strict", action="store_true",
                   help="fail on pathologies/warnings too, not just "
                        "current-round win-criterion losses")
    p.add_argument("--json", action="store_true",
                   help="emit findings as one JSON object instead of text")
    args = p.parse_args(argv)
    code, findings, text = run_doctor(
        args.directory, live=args.live, strict=args.strict
    )
    if args.json:
        print(json.dumps({
            "exit_code": code,
            "findings": [vars(v) for v in findings],
        }, indent=2))
    else:
        print(text)
    return code


if __name__ == "__main__":
    sys.exit(main())
