#!/usr/bin/env python
"""Static parity-convention lints for photon_ml_tpu (CLAUDE.md conventions).

Fourteen checks, all pure-AST (no jax import; runs in milliseconds):

1. **Docstring citations** — every ``photon_ml_tpu/**/*.py`` module (except
   ``__init__.py`` re-export shims) must carry a module docstring that
   either cites a reference source file (``Foo.scala``, ``*.avsc``,
   ``*.java``) or explicitly declares "no reference analogue". This is the
   convention the parity judge checks against SURVEY.md §2.

2. **Forbidden batched decompositions** — XLA's batched small
   decompositions serialize per matrix on TPU (cholesky+cho_solve on
   [2000, 16, 16] = 3.4 ms, LU = 9.0 ms, vs 0.09 ms for the hand-rolled
   vectorized Gauss-Jordan in optim/newton.py — BASELINE.md r5 study), so
   ``jnp.linalg.cholesky`` / ``jnp.linalg.solve`` / ``jnp.linalg.inv`` and
   ``jax.scipy.linalg.cho_*`` calls are banned outside the approved
   modules: ops/variance.py (single-Hessian reference-fidelity path with
   its own size gates) and algorithm/coordinates.py (one shared [k, k]
   Gram solve, not a batch).

3. **Unconditional full reads in cli/** — CLI drivers must ingest through
   the partitioned dispatcher (``io.partitioned_reader.read_partitioned``,
   which delegates to ``read_merged`` single-process): a direct
   ``read_merged`` in a driver silently multiplies the full-input decode
   by the process count on multi-host runs (the r5 host-periphery
   finding; ISSUE 2).

4. **O(n) score gathers** — ``process_allgather`` funnels its operand
   through every host; on score-sized ([n]) arrays that undoes the mesh's
   parallelism and peaks host memory at global size. Calls are banned
   outside the model-sized allowlisted helpers in parallel/distributed.py
   (``_host_scores`` — the documented legacy gather for callers that want
   the full vector — and the ``to_host`` state gathers); new score paths
   go through ``parallel.scoring.DistributedScorer.score_partitioned`` +
   ``io.score_writer.ShardedScoreWriter``.

5. **Broad excepts** — bare ``except:`` / ``except Exception:`` /
   ``except BaseException:`` silently swallow the very failures the
   resilience layer exists to classify (photon_ml_tpu/resilience/errors
   is the ONE reviewed transient-vs-fatal decision point; the r2 "compile
   service flakiness" survived a whole round inside an unattributed catch).
   A broad handler passes only when it RE-RAISES (a ``raise`` statement
   anywhere in the handler — the cleanup-and-propagate pattern) or when
   its (file, function) is on the resilience classifier's reviewed
   allowlist below (capability probes, destructor guards, listener
   isolation).

6. **Pallas in vmapped solve modules** — ``lax.while_loop`` bodies trace
   with UNBATCHED tracers, so a ``pallas_call`` baked into a solver loop
   cannot see the vmap and gets batched into a serial per-lane loop
   (measured 40x slower on the λ-grid, BASELINE.md r4; the reason
   ops/objective.py forces use_pallas=False on every vmapped lane). The
   solver/coordinate modules (``optim/``, ``algorithm/``, estimators.py)
   therefore must not contain a literal ``use_pallas=True`` call keyword,
   any ``pallas_call`` reference, or an import of a pallas module.

7. **segment_sum without num_segments** — a ``jax.ops.segment_sum`` call
   that omits ``num_segments`` infers the segment count from the data,
   silently re-specializing shapes per batch (a fresh compile — ~100 ms
   remote dispatch each — whenever the inferred count changes) and, under
   jit with traced ids, failing outright. Every call in the device hot-path
   packages ``ops/`` and ``parallel/`` must pass the count explicitly
   (keyword or third positional argument).

8. **Dead-end flag rejections in cli/** — a driver-level guard that
   rejects a flag COMBINATION ("cannot combine", "mutually exclusive",
   ...) must tell the operator what to do instead (an actionable verb:
   use/drop/pass/see/disable/read ...). ISSUE 6 turned the
   hybrid x --partitioned-io rejection into a supported composition; the
   rejections that remain must never strand an operator without naming
   the composing alternative or the flag to change.

9. **Nested jit in streaming/serving modules** — every chunk-consuming jit
   in io/stream_reader.py + algorithm/streaming.py +
   algorithm/streaming_game.py must live at module
   scope with the chunk batch in its ARGUMENT list: a jit built inside a
   function can close over chunk-sized arrays, which serialize as
   CONSTANTS into the remote-compile request and blow the tunnel's HTTP
   limit at ~250 MB (the measured 413 landmine). The serving package
   (``photon_ml_tpu/serving/``) is under the same ban: closing a jit over
   the resident model's device arrays is exactly the same landmine —
   params must enter the program as ARGUMENTS (pre-placed, donated
   buffers), and the one construction site that does so is reviewed
   explicitly (JIT_CLOSURE_ALLOWED).

10. **Ungated checkpoint writes in training loops** — every
   ``TrainingCheckpointer``/``SolverCheckpointer`` write site in
   ``parallel/`` and ``algorithm/`` must go through
   ``io.checkpoint.commit_checkpoint`` (rank-0-gated per the
   multi-process convention, barrier-committed when a MetadataExchange is
   attached). A bare ``checkpointer.save(...)`` in a training loop lets a
   worker rank race rank 0 on the shared directory, or commit a
   checkpoint for a sweep some rank never finished (ISSUE 8's
   exchange-consistency rule).

11. **time.time() for durations** — ``time.time()`` is wall clock: it
   steps with NTP/host clock adjustments, so differences of its readings
   are not durations (rows ordered by it can even go backwards — the
   reason journal rows carry ``elapsed_ms``). Every duration/ordering
   measurement in ``photon_ml_tpu/`` must use ``time.perf_counter``.
   ``time.time()`` calls are banned outside the reviewed
   absolute-timestamp allowlist (the journal's ``ts`` field, the tracer's
   wall anchor — sites whose OUTPUT is an absolute timestamp, never a
   difference).

12. **Bench rows without a verdict rule** — every row key
   ``bench.sample_report()`` emits (the ``_row(...)`` metric literals,
   including f-string prefixes like ``fe_hot_loop_hbm_gbps_{label}``) must
   have a registered win criterion in ``telemetry/verdicts.py`` (a
   ``@rule("<key>")`` / ``@rule("<prefix>*")`` decorator literal). A new
   bench row whose "what does winning mean" lives only in prose is exactly
   how BENCH_r04/r05 shipped with ``parsed: null`` unnoticed — the doctor
   (dev/doctor.py) can only judge rows the registry covers, so the
   coverage is enforced statically.

13. **Raw jit sites in the hot-program packages** — every jit in
   ``algorithm/``, ``serving/`` and ``parallel/`` must route through
   ``telemetry.program_ledger.ledger_jit`` with a stable label (the
   lint-as-memory discipline: labeling hot programs is structural, not
   remembered), or sit on the reviewed class-qualified allowlist. A raw
   ``jax.jit`` there compiles programs the ledger cannot see — its
   recompile attribution, cost accounting, and the serving
   ``replay_compiles == 0`` pin (ISSUE 13) all go blind to that site.

14. **Resident-param mutation outside the guarded swap API** — the
   serving package holds a model resident across requests; swapping it
   in-place is legal ONLY through ``ResidentScorer.swap_model``, whose
   layout fingerprint guard rejects a layout-changing model typed (naming
   the differing leaves) BEFORE any state mutates and re-feeds the
   resident-bytes/HBM-forecast gauges after. An assignment to a
   resident-param attribute (``.model``, the params caches) anywhere else
   in ``photon_ml_tpu/serving/`` would bypass that guard — a silent
   layout change recompiles per request (the bounded-signature contract
   dies) or serves garbage. Class-qualified allowlist, like checks 9-13.

Exit status 0 = clean; 1 = violations (printed one per line as
``path:lineno: message``). Run from the repo root:

    python dev/lint_parity.py
"""

from __future__ import annotations

import ast
import pathlib
import re
import sys

PACKAGE = "photon_ml_tpu"

#: a docstring satisfies the convention if it names a reference source file
#: (Foo.scala:NN and friends; dev-scripts/*.py is the reference's one Python
#: tool — a bare .py mention is NOT enough, else self-citations of this
#: package's own modules would pass), a reference module directory
#: (photon-diagnostics diagnostics/hl/ — used by subsystem-level ports), or
#: explicitly declares there is none
CITATION_RE = re.compile(
    r"\.(scala|avsc|java)\b"
    r"|dev-scripts/[\w./-]+\.py\b"
    r"|photon-(lib|api|client|diagnostics|test-utils)\s+[\w./-]+/"
    r"|no reference analogue",
    re.IGNORECASE,
)

#: modules allowed to call the banned decompositions (see module docstring)
LINALG_ALLOWED = {
    f"{PACKAGE}/ops/variance.py",
    f"{PACKAGE}/algorithm/coordinates.py",
}

#: jnp.linalg attributes that batch-serialize on TPU. Host-side numpy
#: (np.linalg.*) is NOT banned — the measured pathology is TPU-only.
BANNED_LINALG = {"cholesky", "solve", "inv", "cho_factor", "cho_solve"}

#: attribute-chain roots that resolve to jax (import jax / import jax.numpy
#: as jnp / import jax.scipy as jsp conventions in this repo)
JAX_ROOTS = {"jax", "jnp", "jsp"}


def _jax_linalg_aliases(tree: ast.AST) -> set[str]:
    """Names bound to a jax linalg MODULE (``from jax.numpy import linalg``
    / ``from jax.scipy import linalg as jla``) — calls through these would
    otherwise produce 2-element chains that escape the root check."""
    aliases: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in (
            "jax.numpy", "jax.scipy", "jax"
        ):
            for a in node.names:
                if a.name == "linalg":
                    aliases.add(a.asname or a.name)
    return aliases


def _attribute_chain(node: ast.Attribute) -> list[str]:
    """`jnp.linalg.solve` -> ["jnp", "linalg", "solve"]."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return parts[::-1]


def check_docstring_citations(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE).rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if path.name == "__init__.py":
            continue  # re-export shims; parity docs live in the modules
        tree = ast.parse(path.read_text())
        doc = ast.get_docstring(tree) or ""
        if not CITATION_RE.search(doc):
            problems.append(
                f"{rel}:1: module docstring cites no reference file "
                "(want e.g. 'Foo.scala:NN' or an explicit "
                "'no reference analogue')"
            )
    return problems


def check_banned_linalg(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE).rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if rel in LINALG_ALLOWED:
            continue
        tree = ast.parse(path.read_text())
        aliases = _jax_linalg_aliases(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Attribute):
                continue
            chain = _attribute_chain(node)
            if len(chain) < 2 or chain[-1] not in BANNED_LINALG:
                continue
            # jnp.linalg.solve / jax.numpy.linalg.solve / jsp.linalg.cho_solve
            via_root = (
                len(chain) >= 3 and chain[-2] == "linalg"
                and chain[0] in JAX_ROOTS
            )
            # from jax.numpy import linalg [as X]; X.solve(...)
            via_alias = len(chain) == 2 and chain[0] in aliases
            if via_root or via_alias:
                problems.append(
                    f"{rel}:{node.lineno}: {'.'.join(chain)} — batched "
                    "small decompositions serialize per matrix on TPU; use "
                    "the vectorized Gauss-Jordan path (optim/newton.py / "
                    "ops/variance.py) or add this module to the lint "
                    "allowlist with a measured justification"
                )
    return problems


#: (file, function) pairs whose process_allgather calls are model-sized
#: and reviewed: _host_scores (the documented legacy full-vector gather)
#: and the nested to_host state gathers — a same-named function in any
#: OTHER module does not inherit the exemption
ALLGATHER_ALLOWED = {
    (f"{PACKAGE}/parallel/distributed.py", "_host_scores"),
    (f"{PACKAGE}/parallel/distributed.py", "to_host"),
    # SPMD lane scheduling: per-LANE scalars (entity-table-sized flags and
    # traces, never the [n] sample axis), a collective every rank makes
    (f"{PACKAGE}/algorithm/lane_scheduler.py", "_gather_np"),
}


def check_cli_full_reads(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE / "cli").glob("*.py")):
        rel = path.relative_to(root).as_posix()
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.ImportFrom) and any(
                a.name == "read_merged" for a in node.names
            ):
                hit = "import of read_merged"
            elif isinstance(node, ast.Name) and node.id == "read_merged":
                hit = "read_merged"
            elif isinstance(node, ast.Attribute) and node.attr == "read_merged":
                hit = "read_merged"
            if hit:
                problems.append(
                    f"{rel}:{node.lineno}: {hit} — CLI drivers must ingest "
                    "through io.partitioned_reader.read_partitioned (it "
                    "delegates to read_merged single-process; a direct "
                    "call multiplies the full decode by the process count)"
                )
    return problems


def check_score_allgathers(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE).rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        tree = ast.parse(path.read_text())

        stack: list[str] = []
        hits: list[int] = []

        def visit(node):
            is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            if is_fn:
                stack.append(node.name)
            if (
                (isinstance(node, ast.Attribute)
                 and node.attr == "process_allgather")
                or (isinstance(node, ast.Name)
                    and node.id == "process_allgather")
            ) and not (stack and (rel, stack[-1]) in ALLGATHER_ALLOWED):
                hits.append(node.lineno)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_fn:
                stack.pop()

        visit(tree)
        for lineno in hits:
            problems.append(
                f"{rel}:{lineno}: process_allgather outside the allowlisted "
                "model-sized helpers — an O(n) score gather funnels the "
                "global vector through every host; use "
                "DistributedScorer.score_partitioned + ShardedScoreWriter, "
                "or put a model-sized gather in an allowlisted helper"
            )
    return problems


#: the resilience classifier's allowlist: (file, function) pairs whose
#: broad excepts are REVIEWED swallows — capability probes whose failure
#: IS the answer, destructor/listener isolation, and the classifier
#: consumers themselves (resilience/policy.py, resilience/recovery.py:
#: their handlers consult classify_exception and re-raise fatal errors).
#: Everything else must catch typed exceptions or re-raise.
BROAD_EXCEPT_ALLOWED = {
    (f"{PACKAGE}/resilience/policy.py", "call"),
    (f"{PACKAGE}/resilience/recovery.py", "run_with_recovery"),
    # the chunk-prefetch producer thread: the retry policy already
    # classified and retried; a thread cannot re-raise usefully, so the
    # handler classifies and FORWARDS the failure to the consumer's
    # stack, which re-raises it attributed (io/stream_reader.py)
    (f"{PACKAGE}/io/stream_reader.py", "_producer"),
    (f"{PACKAGE}/telemetry/probes.py", "live_buffer_bytes"),
    # same allocator capability probe as live_buffer_bytes: no
    # memory_stats means no limit, and None IS the answer
    (f"{PACKAGE}/telemetry/probes.py", "device_memory_limit_bytes"),
    # the program ledger's cost/memory analysis is a capability probe:
    # lower()/cost_analysis()/AOT compile each fail differently per
    # backend, every failure degrades to None fields (logged at debug),
    # and an analysis error must never reach the dispatch path it observes
    (f"{PACKAGE}/telemetry/program_ledger.py", "_analyze"),
    (f"{PACKAGE}/telemetry/journal.py", "_process_index"),
    # same capability probe as the journal's: rank 0 when jax is absent
    (f"{PACKAGE}/telemetry/tracing.py", "_process_index"),
    # driver-teardown trace flush: tracing is observability — an error in
    # a finally must not replace the run's own outcome or skip the
    # journal rows that follow; every error is logged with traceback
    (f"{PACKAGE}/telemetry/tracing.py", "flush_trace_best_effort"),
    (f"{PACKAGE}/io/offheap_index_map.py", "__del__"),
    (f"{PACKAGE}/native/build.py", "native_available"),
    (f"{PACKAGE}/native/build.py", "libsvm_native_available"),
    (f"{PACKAGE}/native/build.py", "avro_native_available"),
    (f"{PACKAGE}/util/timed.py", "__enter__"),
    (f"{PACKAGE}/util/events.py", "send"),
    (f"{PACKAGE}/cli/game_training_driver.py", "validate"),
    # the serve driver's swap-poller daemon thread: a garbled published
    # model dir can raise beyond the obvious types, the thread has no
    # caller to re-raise to, and one bad publish must never stop all
    # future refreshes — every failure is journaled as a typed
    # `model_swap` rejection and classified for log severity
    (f"{PACKAGE}/cli/serve_driver.py", "scan_once"),
    # the serving micro-batch loop: a batch-level scoring failure routes
    # through classify_exception and falls back to per-request isolation
    # (_isolate), where each request's own failure is classified and
    # forwarded TYPED to that request's future — one poisoned request
    # fails attributed, the loop keeps serving (the chaos-suite contract)
    (f"{PACKAGE}/serving/batching.py", "_flush"),
    (f"{PACKAGE}/serving/batching.py", "_isolate"),
}

_BROAD_NAMES = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except:
        return True
    if isinstance(t, ast.Name) and t.id in _BROAD_NAMES:
        return True
    if isinstance(t, ast.Tuple):
        return any(
            isinstance(e, ast.Name) and e.id in _BROAD_NAMES for e in t.elts
        )
    return False


def _reraises(handler: ast.ExceptHandler) -> bool:
    """Any raise in the handler body: cleanup-and-propagate (bare
    ``raise``) or typed transformation (``raise X(...) from e``) — the
    original failure is not swallowed either way."""
    return any(
        isinstance(node, ast.Raise)
        for stmt in handler.body
        for node in ast.walk(stmt)
    )


def check_broad_excepts(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE).rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        tree = ast.parse(path.read_text())

        stack: list[str] = []

        def visit(node):
            is_fn = isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            if is_fn:
                stack.append(node.name)
            if (
                isinstance(node, ast.ExceptHandler)
                and _is_broad(node)
                and not _reraises(node)
                and not (stack and (rel, stack[-1]) in BROAD_EXCEPT_ALLOWED)
            ):
                problems.append(
                    f"{rel}:{node.lineno}: broad except "
                    "(bare/Exception/BaseException) that swallows the "
                    "error — catch typed exceptions, re-raise, or route "
                    "the decision through resilience.classify_exception "
                    "and add the (file, function) to the reviewed "
                    "allowlist in dev/lint_parity.py"
                )
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_fn:
                stack.pop()

        visit(tree)
    return problems


#: modules whose solves are vmapped (per-entity RE/MF buckets, λ-grid
#: lanes): a Pallas kernel reachable from them vmap-batches into a serial
#: per-lane loop (the measured 40x footgun — check 6 above)
VMAPPED_SOLVE_PREFIXES = (
    f"{PACKAGE}/optim/",
    f"{PACKAGE}/algorithm/",
    f"{PACKAGE}/estimators.py",
    # search tournaments (ISSUE 20) dispatch the same vmapped lane solves
    f"{PACKAGE}/hyperparameter/",
)

_PALLAS_MODULE_RE = re.compile(r"(^|\.)pallas(\b|_glm)")


def check_vmapped_pallas(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE).rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if not rel.startswith(VMAPPED_SOLVE_PREFIXES):
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            hit = None
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (
                        kw.arg == "use_pallas"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value is True
                    ):
                        hit = "use_pallas=True"
            elif isinstance(node, ast.Name) and node.id == "pallas_call":
                hit = "pallas_call"
            elif isinstance(node, ast.Attribute) and node.attr == "pallas_call":
                hit = "pallas_call"
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                mods = [a.name for a in node.names]
                if isinstance(node, ast.ImportFrom) and node.module:
                    mods.append(node.module)
                if any(_PALLAS_MODULE_RE.search(m) for m in mods):
                    hit = "pallas import"
            if hit:
                problems.append(
                    f"{rel}:{node.lineno}: {hit} in a vmapped-solve module — "
                    "while_loop bodies trace unbatched, so a baked-in Pallas "
                    "call gets vmap-batched into a serial per-lane loop "
                    "(measured 40x slower); keep use_pallas=False on vmapped "
                    "lanes (ops/objective.py)"
                )
    return problems


#: packages whose segment_sum calls run in device hot paths (check 7); a
#: missing num_segments there silently re-specializes shapes per batch
SEGMENT_SUM_CHECKED_PREFIXES = (
    f"{PACKAGE}/ops/",
    f"{PACKAGE}/parallel/",
)


def check_segment_sum_num_segments(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE).rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if not rel.startswith(SEGMENT_SUM_CHECKED_PREFIXES):
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_seg = (
                isinstance(fn, ast.Attribute) and fn.attr == "segment_sum"
            ) or (isinstance(fn, ast.Name) and fn.id == "segment_sum")
            if not is_seg:
                continue
            explicit = len(node.args) >= 3 or any(
                kw.arg == "num_segments" for kw in node.keywords
            )
            if not explicit:
                problems.append(
                    f"{rel}:{node.lineno}: segment_sum without an explicit "
                    "num_segments= — the inferred count re-specializes "
                    "shapes per batch (a fresh remote compile whenever it "
                    "changes) and fails under jit with traced ids; pass "
                    "the static segment count"
                )
    return problems


#: a rejection message is a flag-COMBINATION rejection when it says two
#: things cannot be used together (check 8)
COMBINATION_REJECTION_RE = re.compile(
    r"cannot (?:be )?combined?\b|does not combine|mutually exclusive",
    re.IGNORECASE,
)

#: ...and it escapes the dead-end when it names an actionable alternative
REJECTION_POINTER_RE = re.compile(
    r"\b(use|instead|drop|pass|see|disable|switch|read|set)\b",
    re.IGNORECASE,
)


def _literal_message(call: ast.Call) -> str:
    """Concatenate the string-literal fragments of a call's arguments
    (implicit adjacent-literal concatenation arrives as one Constant;
    f-string constant parts ride JoinedStr values)."""
    parts: list[str] = []
    for arg in call.args:
        for node in ast.walk(arg):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                parts.append(node.value)
    return "".join(parts)


def check_cli_dead_end_rejections(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE / "cli").glob("*.py")):
        rel = path.relative_to(root).as_posix()
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            is_guard = (
                isinstance(fn, ast.Name) and fn.id == "ValueError"
            ) or (isinstance(fn, ast.Attribute) and fn.attr == "append")
            if not is_guard:
                continue
            msg = _literal_message(node)
            if not COMBINATION_REJECTION_RE.search(msg):
                continue
            if not REJECTION_POINTER_RE.search(msg):
                problems.append(
                    f"{rel}:{node.lineno}: flag-combination rejection "
                    "without a pointer to the composing alternative — tell "
                    "the operator what to use/drop/change instead (no "
                    "dead-end rejections; see ISSUE 6 / lint check 8)"
                )
    return problems


#: the out-of-core streaming modules (check 9): every chunk-consuming jit
#: must live at module scope with the chunk batch in its ARGUMENT list — a
#: jit built inside a function can close over chunk-sized arrays, which
#: serialize as CONSTANTS into the remote-compile request and blow the
#: tunnel's HTTP limit at ~250 MB (the measured 413 landmine)
STREAMING_MODULES = (
    f"{PACKAGE}/io/stream_reader.py",
    f"{PACKAGE}/algorithm/streaming.py",
    # the streamed-GAME path (ISSUE 11): its chunk-consuming jits carry
    # the same 413 exposure as the GLM streaming modules
    f"{PACKAGE}/algorithm/streaming_game.py",
    # model-search tournaments (ISSUE 20): the vmapped lane solve and the
    # on-device metric jits take the full train/validation batch — it must
    # ride the argument list, never a closure
    f"{PACKAGE}/algorithm/lane_search.py",
    f"{PACKAGE}/hyperparameter/search_driver.py",
)

#: serving modules join the ban (whole package): the operand at risk is
#: the resident MODEL's device arrays instead of a chunk, same 413 physics
SERVING_MODULE_PREFIX = f"{PACKAGE}/serving/"

#: (file, dotted class-qualified scope) pairs whose jit CONSTRUCTION is
#: reviewed: the resident scorer builds its donated-buffer program once at
#: startup, and BOTH operands — micro-batch data and pre-placed model
#: params — enter it as ARGUMENTS (nothing request- or model-sized is
#: closed over; see the site's comment). Class-qualified so another jit in
#: the same file stays banned.
JIT_CLOSURE_ALLOWED = {
    (f"{PACKAGE}/serving/resident.py", "ResidentScorer.__init__"),
}


#: names check 9 treats as a jit constructor: the raw jax.jit and the
#: ledger's labeled wrapper (telemetry/program_ledger.ledger_jit) — the
#: closure discipline is identical either way (operands must be ARGUMENTS)
_JIT_NAMES = ("jit", "ledger_jit")


def _jit_references(node: ast.AST):
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in _JIT_NAMES:
            yield n
        elif isinstance(n, ast.Name) and n.id in _JIT_NAMES:
            yield n


def check_streaming_jit_closures(root: pathlib.Path) -> list[str]:
    problems = []
    paths = [root / rel for rel in STREAMING_MODULES]
    serving_dir = root / SERVING_MODULE_PREFIX
    if serving_dir.is_dir():
        paths.extend(sorted(serving_dir.rglob("*.py")))
    for path in paths:
        if not path.exists():
            continue
        rel = path.relative_to(root).as_posix()
        tree = ast.parse(path.read_text())
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # module-scope decorator jits are the sanctioned form —
                # compiled once, chunks enter through the argument list —
                # but the chunk batch must actually BE an argument
                deco_jits = [
                    n for d in stmt.decorator_list for n in _jit_references(d)
                ]
                args = {
                    a.arg
                    for a in (
                        stmt.args.posonlyargs
                        + stmt.args.args
                        + stmt.args.kwonlyargs
                    )
                }
                if deco_jits and "batch" not in args:
                    problems.append(
                        f"{rel}:{stmt.lineno}: module-level jit "
                        f"'{stmt.name}' has no 'batch' parameter — the "
                        "chunk must ride the jit's argument list, never a "
                        "closure (the HTTP-413 landmine; lint check 9)"
                    )
        problems.extend(_nested_jit_hits(rel, tree))
    return problems


def _nested_jit_hits(rel: str, tree: ast.AST) -> list[str]:
    """jit references outside the sanctioned module-scope-decorator form,
    minus the reviewed JIT_CLOSURE_ALLOWED construction sites (tracked by
    dotted class-qualified scope name)."""
    problems: list[str] = []

    def flag(node) -> None:
        problems.append(
            f"{rel}:{node.lineno}: jit nested inside a function/class in "
            "a streaming/serving module — a jit built per call can close "
            "over chunk- or model-sized arrays, which serialize as "
            "constants into the remote-compile request (HTTP 413 past "
            "~250 MB); define the jitted step at module scope (or a "
            "reviewed JIT_CLOSURE_ALLOWED site) and pass the operands as "
            "arguments (lint check 9)"
        )

    def scan(node, stack: "tuple[str, ...]") -> None:
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            inner = stack + (node.name,)
            for child in ast.iter_child_nodes(node):
                scan(child, inner)
            return
        is_jit = (
            isinstance(node, ast.Attribute) and node.attr in _JIT_NAMES
        ) or (isinstance(node, ast.Name) and node.id in _JIT_NAMES)
        if is_jit and not (
            stack and (rel, ".".join(stack)) in JIT_CLOSURE_ALLOWED
        ):
            flag(node)
        for child in ast.iter_child_nodes(node):
            scan(child, stack)

    for stmt in tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # decorators are judged by the module-scope 'batch' rule above
            for child in stmt.body:
                scan(child, (stmt.name,))
        else:
            scan(stmt, ())
    return problems


#: training-loop packages whose checkpoint writes must ride the commit
#: helper (check 10); io/ itself (the helper + checkpointer internals)
#: and estimators/cli (single-rank solver checkpointing, rank-gated at
#: the library layer) are out of scope
CHECKPOINT_WRITE_PREFIXES = (
    f"{PACKAGE}/parallel/",
    f"{PACKAGE}/algorithm/",
)

#: a receiver is "a checkpointer" when any identifier in its attribute
#: chain mentions one — matches this repo's naming (checkpointer, ckpt,
#: self.checkpointer); a same-named method on unrelated objects
#: (imap.save, model saves) never matches
_CHECKPOINTER_NAME_RE = re.compile(r"checkpoint|(^|\.)ckpt(\.|$)",
                                   re.IGNORECASE)


def check_checkpoint_commit_sites(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE).rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if not rel.startswith(CHECKPOINT_WRITE_PREFIXES):
            continue
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("save", "save_progress")
            ):
                continue
            receiver = ".".join(_attribute_chain(fn)[:-1])
            if _CHECKPOINTER_NAME_RE.search(receiver):
                problems.append(
                    f"{rel}:{node.lineno}: direct checkpointer "
                    f"{fn.attr}() in a training-loop module — multi-rank "
                    "checkpoint writes must go through io.checkpoint."
                    "commit_checkpoint (rank-0-gated, barrier-committed; "
                    "lint check 10)"
                )
    return problems


#: (file, dotted class-qualified name) pairs whose ``time.time()`` reads
#: are REVIEWED absolute-timestamp sites (the value is reported as a
#: wall-clock stamp, never differenced): the journal's per-row ``ts`` and
#: the tracer's wall anchor for cross-rank correlation. Class-QUALIFIED so
#: e.g. a time.time() in another __init__ of the same file stays banned.
#: Everything else must use ``time.perf_counter`` (check 11).
TIME_TIME_ALLOWED = {
    (f"{PACKAGE}/telemetry/journal.py", "RunJournal.record"),
    (f"{PACKAGE}/telemetry/tracing.py", "Tracer.__init__"),
}


def check_time_time_durations(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE).rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        tree = ast.parse(path.read_text())
        # names bound to time.time by `from time import time [as t]`
        aliases: set[str] = set()
        # names bound to the time MODULE (`import time [as clock]`) so
        # `clock.time()` cannot slip past the receiver-name check
        module_aliases: set[str] = {"time"}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "time":
                for a in node.names:
                    if a.name == "time":
                        aliases.add(a.asname or a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "time":
                        module_aliases.add(a.asname or a.name)

        stack: list[str] = []
        hits: list[int] = []

        def visit(node):
            is_scope = isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            )
            if is_scope:
                stack.append(node.name)
            if isinstance(node, ast.Call):
                fn = node.func
                is_time = (
                    isinstance(fn, ast.Attribute)
                    and fn.attr == "time"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in module_aliases
                ) or (isinstance(fn, ast.Name) and fn.id in aliases)
                if is_time and (rel, ".".join(stack)) not in TIME_TIME_ALLOWED:
                    hits.append(node.lineno)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()

        visit(tree)
        for lineno in hits:
            problems.append(
                f"{rel}:{lineno}: time.time() — wall clock steps with host "
                "clock adjustments, so its differences are not durations; "
                "use time.perf_counter for any timing/ordering, or add "
                "this reviewed absolute-timestamp site to "
                "TIME_TIME_ALLOWED in dev/lint_parity.py (check 11)"
            )
    return problems


#: hot-program packages whose jits must carry a ledger label (check 13):
#: a raw jax.jit here compiles programs the ledger cannot attribute
RAW_JIT_PREFIXES = (
    f"{PACKAGE}/algorithm/",
    f"{PACKAGE}/serving/",
    f"{PACKAGE}/parallel/",
    f"{PACKAGE}/hyperparameter/",
)

#: (file, dotted class-qualified scope) pairs whose RAW jax.jit use is
#: reviewed — currently empty: every jit in the checked packages routes
#: through ledger_jit. Add an entry only with a written reason the site
#: cannot carry a label.
RAW_JIT_ALLOWED: set = set()


def check_raw_jit_sites(root: pathlib.Path) -> list[str]:
    problems = []
    for path in sorted((root / PACKAGE).rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        if not rel.startswith(RAW_JIT_PREFIXES):
            continue
        tree = ast.parse(path.read_text())
        # names bound to jax.jit by `from jax import jit [as j]`
        aliases: set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "jax":
                for a in node.names:
                    if a.name == "jit":
                        aliases.add(a.asname or a.name)

        stack: list[str] = []
        hits: list[int] = []

        def visit(node):
            is_scope = isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            )
            if is_scope:
                stack.append(node.name)
            raw = (
                isinstance(node, ast.Attribute) and node.attr == "jit"
                and isinstance(node.value, ast.Name)
                and node.value.id in JAX_ROOTS
            ) or (isinstance(node, ast.Name) and node.id in aliases)
            if raw and (rel, ".".join(stack)) not in RAW_JIT_ALLOWED:
                hits.append(node.lineno)
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()

        visit(tree)
        for lineno in hits:
            problems.append(
                f"{rel}:{lineno}: raw jax.jit in a hot-program package — "
                "route the site through telemetry.program_ledger.ledger_jit "
                "with a stable label so the program ledger can attribute "
                "its compiles (ISSUE 13), or add the class-qualified scope "
                "to RAW_JIT_ALLOWED with a written reason (lint check 13)"
            )
    return problems


#: resident-param attributes whose assignment in serving/ must route
#: through the guarded swap API (check 14): the resident model reference
#: and the layout-keyed params caches it invalidates
RESIDENT_PARAM_ATTRS = {
    "model",
    "_params_cache",
    "_bf16_params_cache",
    "_params_cache_bytes",
    "_kinds",
    "_model_version",
}

#: (file, dotted class-qualified scope) pairs sanctioned to mutate
#: resident params: construction, and the fingerprint-guarded swap
RESIDENT_MUTATION_ALLOWED = {
    (f"{PACKAGE}/serving/resident.py", "ResidentScorer.__init__"),
    (f"{PACKAGE}/serving/resident.py", "ResidentScorer.swap_model"),
}


def check_resident_param_mutations(root: pathlib.Path) -> list[str]:
    problems = []
    serving_dir = root / PACKAGE / "serving"
    if not serving_dir.is_dir():
        return problems
    for path in sorted(serving_dir.rglob("*.py")):
        rel = path.relative_to(root).as_posix()
        tree = ast.parse(path.read_text())

        stack: list[str] = []
        hits: list[tuple[int, str]] = []

        def flatten(t):
            # tuple/list unpacking and starred targets must not slip the
            # ban: `self.model, x = ...` mutates resident params too
            if isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    yield from flatten(e)
            elif isinstance(t, ast.Starred):
                yield from flatten(t.value)
            else:
                yield t

        def targets(node):
            raw = []
            if isinstance(node, ast.Assign):
                raw = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                raw = [node.target]
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                raw = [node.target]
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                raw = [
                    item.optional_vars for item in node.items
                    if item.optional_vars is not None
                ]
            return [t for r in raw for t in flatten(r)]

        def visit(node):
            is_scope = isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            )
            if is_scope:
                stack.append(node.name)
            for t in targets(node):
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr in RESIDENT_PARAM_ATTRS
                    and (rel, ".".join(stack)) not in RESIDENT_MUTATION_ALLOWED
                ):
                    hits.append((node.lineno, t.attr))
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_scope:
                stack.pop()

        visit(tree)
        for lineno, attr in hits:
            problems.append(
                f"{rel}:{lineno}: assignment to resident-param attribute "
                f"'.{attr}' outside the guarded swap API — resident-model "
                "mutation in serving/ must go through "
                "ResidentScorer.swap_model (layout-fingerprint-guarded, "
                "gauge-refeeding) or a reviewed "
                "RESIDENT_MUTATION_ALLOWED scope (lint check 14)"
            )
    return problems


#: where check 12 reads its two sides from (relative to the lint root)
BENCH_MODULE = "bench.py"
VERDICTS_MODULE = f"{PACKAGE}/telemetry/verdicts.py"


def _bench_row_keys(tree: ast.AST) -> list[tuple[str, bool, int]]:
    """(key, is_prefix, lineno) for every ``_row(...)`` first argument in
    ``sample_report()`` — string literals exactly, f-strings as the leading
    constant prefix (``fe_hot_loop_hbm_gbps_{label}`` ->
    ``fe_hot_loop_hbm_gbps_`` + is_prefix)."""
    keys: list[tuple[str, bool, int]] = []
    fn = next(
        (n for n in ast.walk(tree)
         if isinstance(n, ast.FunctionDef) and n.name == "sample_report"),
        None,
    )
    if fn is None:
        return keys
    for node in ast.walk(fn):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "_row"
            and node.args
        ):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            keys.append((arg.value, False, node.lineno))
        elif isinstance(arg, ast.JoinedStr):
            prefix = ""
            for part in arg.values:
                if isinstance(part, ast.Constant) and isinstance(
                    part.value, str
                ):
                    prefix += part.value
                else:
                    break
            if prefix:
                keys.append((prefix, True, node.lineno))
    return keys


def _verdict_rule_patterns(tree: ast.AST) -> set[str]:
    """String-literal first arguments of ``@rule(...)`` decorators in
    telemetry/verdicts.py — the statically readable registry surface."""
    patterns: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for deco in node.decorator_list:
            if (
                isinstance(deco, ast.Call)
                and isinstance(deco.func, ast.Name)
                and deco.func.id == "rule"
                and deco.args
                and isinstance(deco.args[0], ast.Constant)
                and isinstance(deco.args[0].value, str)
            ):
                patterns.add(deco.args[0].value)
    return patterns


def check_bench_verdict_rules(root: pathlib.Path) -> list[str]:
    bench_path = root / BENCH_MODULE
    verdicts_path = root / VERDICTS_MODULE
    if not bench_path.exists() or not verdicts_path.exists():
        return []  # synthetic lint roots without a bench surface
    keys = _bench_row_keys(ast.parse(bench_path.read_text()))
    patterns = _verdict_rule_patterns(ast.parse(verdicts_path.read_text()))
    stems = {p[:-1] for p in patterns if p.endswith("*")}
    problems = []
    for key, is_prefix, lineno in keys:
        if is_prefix:
            # SOUND direction only: every key the f-string can generate is
            # key+<suffix>, which matches a glob stem s iff the generated
            # key startswith s — guaranteed for all suffixes only when the
            # literal prefix already contains the stem. (s.startswith(key)
            # would accept `f"fe_{x}"` against stem "fe_hot_loop_…" while
            # rule_for("fe_other") matches nothing at runtime.)
            matched = any(key.startswith(s) for s in stems)
        else:
            matched = key in patterns or any(
                key.startswith(s) for s in stems
            )
        if not matched:
            problems.append(
                f"{BENCH_MODULE}:{lineno}: bench row {key!r}"
                f"{' (f-string prefix)' if is_prefix else ''} has no "
                "registered verdict rule — add @rule(...) with its win "
                "criterion in telemetry/verdicts.py so dev/doctor.py can "
                "judge the row (lint check 12)"
            )
    return problems


def run_lints(root: pathlib.Path | str | None = None) -> list[str]:
    root = pathlib.Path(root) if root else pathlib.Path(__file__).resolve().parents[1]
    return (
        check_docstring_citations(root)
        + check_banned_linalg(root)
        + check_cli_full_reads(root)
        + check_score_allgathers(root)
        + check_broad_excepts(root)
        + check_vmapped_pallas(root)
        + check_segment_sum_num_segments(root)
        + check_cli_dead_end_rejections(root)
        + check_streaming_jit_closures(root)
        + check_checkpoint_commit_sites(root)
        + check_time_time_durations(root)
        + check_bench_verdict_rules(root)
        + check_raw_jit_sites(root)
        + check_resident_param_mutations(root)
    )


def main() -> int:
    problems = run_lints()
    for p in problems:
        print(p)
    if problems:
        print(f"lint_parity: {len(problems)} violation(s)", file=sys.stderr)
        return 1
    print("lint_parity: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
