#!/usr/bin/env python
"""Offline run-trace digest: merge per-rank Chrome-trace files and print
the slow-run report a human pastes into an issue.

Input: a ``--trace-dir`` directory (or explicit ``trace-*.json`` paths)
written by ``telemetry/tracing.py``. Output, to stdout:

1. **Top spans by self-time** — per span name, total duration minus the
   time spent in directly nested spans on the same (rank, thread) lane,
   so an epoch that spends all its time inside accumulate steps does not
   double-count. This is where the wall-clock went.
2. **Per-rank exchange-wait table** — for every exchange tag (digit runs
   collapsed, so per-step/per-seq tags pool), each rank's total blocking
   wait plus the named straggler: the rank that arrived LAST (least
   wait — everyone else's wait is caused by it) or never arrived at all
   (wedged/crashed). This is WHO the wall-clock went to.

Span times are host wall-clock only (BASELINE.md "Trace methodology
r12"): compare fractions within one trace, never absolutes across runs.

    python dev/trace_summary.py /path/to/trace-dir [--top 15]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from collections import defaultdict

# the merge rules live with the tracer so online (run-end exchange) and
# offline (this tool) reports cannot drift — incl. which span names count
# as exchange waits
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from photon_ml_tpu.telemetry.tracing import (  # noqa: E402
    _WAIT_SPAN_NAMES,
    normalize_tag,
    straggler_report,
)


def load_trace_events(path: str) -> list[dict]:
    """One file's complete ("X") events, with ``end`` precomputed."""
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    out = []
    for ev in events:
        if ev.get("ph") != "X":
            continue
        ev = dict(ev)
        ev["end"] = ev["ts"] + ev["dur"]
        out.append(ev)
    return out


def find_trace_files(paths: "list[str]") -> list[str]:
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "trace-*.json"))))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(f"no trace-*.json under {paths!r}")
    return files


def self_times(events: "list[dict]") -> dict[str, dict]:
    """Per span name: {"total_s", "self_s", "count"} — self time excludes
    directly nested spans on the same (pid, tid) lane (stack sweep over
    start-ordered intervals; a child subtracts from its immediate parent
    only)."""
    lanes: dict[tuple, list[dict]] = defaultdict(list)
    for ev in events:
        lanes[(ev["pid"], ev["tid"])].append(ev)
    stats: dict[str, dict] = defaultdict(
        lambda: {"total_s": 0.0, "self_s": 0.0, "count": 0}
    )
    for lane in lanes.values():
        lane.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: list[dict] = []
        selfs: dict[int, float] = {}
        for ev in lane:
            while stack and stack[-1]["end"] <= ev["ts"]:
                stack.pop()
            if stack:
                selfs[id(stack[-1])] -= ev["dur"]
            selfs[id(ev)] = ev["dur"]
            stack.append(ev)
        for ev in lane:
            row = stats[ev["name"]]
            row["total_s"] += ev["dur"] / 1e6
            row["self_s"] += max(0.0, selfs[id(ev)]) / 1e6
            row["count"] += 1
    return dict(stats)


def exchange_wait_tables(events: "list[dict]") -> dict[int, dict]:
    """{rank: {tag: {"count", "wait_s", "max_s"}}} from merged events —
    the offline twin of tracing.exchange_wait_tables (rank from the span's
    ``rank`` arg, falling back to the file's pid)."""
    tables: dict[int, dict] = {}
    for ev in events:
        if ev["name"] not in _WAIT_SPAN_NAMES:
            continue
        args = ev.get("args") or {}
        rank = int(args.get("rank", ev["pid"]))
        tag = normalize_tag(str(args.get("tag", "")))
        row = tables.setdefault(rank, {}).setdefault(
            tag, {"count": 0, "wait_s": 0.0, "max_s": 0.0}
        )
        dur_s = ev["dur"] / 1e6
        row["count"] += 1
        row["wait_s"] += dur_s
        row["max_s"] = max(row["max_s"], dur_s)
    return tables


def format_report(events: "list[dict]", *, top: int = 15) -> str:
    lines: list[str] = []
    stats = self_times(events)
    ranked = sorted(stats.items(), key=lambda kv: -kv[1]["self_s"])[:top]
    lines.append(f"top {len(ranked)} spans by self-time")
    lines.append(f"{'span':<36} {'self s':>10} {'total s':>10} {'count':>8}")
    for name, row in ranked:
        lines.append(
            f"{name:<36} {row['self_s']:>10.3f} {row['total_s']:>10.3f} "
            f"{row['count']:>8d}"
        )

    tables = exchange_wait_tables(events)
    if tables:
        report = straggler_report(tables)
        n = report["num_ranks"]
        lines.append("")
        lines.append("per-rank exchange wait (s) — straggler = rank others "
                     "waited for (least wait / never arrived)")
        header = f"{'tag':<40}" + "".join(
            f"{f'rank {r}':>10}" for r in range(n)
        ) + "  straggler"
        lines.append(header)
        for row in report["tags"]:
            waits = "".join(
                f"{'-':>10}" if w is None else f"{w:>10.3f}"
                for w in row["wait_s"]
            )
            who = (
                "-" if row["straggler_rank"] is None
                else f"rank {row['straggler_rank']} ({row['reason']})"
            )
            lines.append(f"{row['tag']:<40}{waits}  {who}")
    else:
        lines.append("")
        lines.append("no exchange spans (single-rank or untraced exchanges)")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+",
                   help="trace dir(s) or trace-*.json files")
    p.add_argument("--top", type=int, default=15,
                   help="how many spans in the self-time table")
    args = p.parse_args(argv)
    events: list[dict] = []
    for f in find_trace_files(args.paths):
        events.extend(load_trace_events(f))
    print(format_report(events, top=args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
